"""Autoregressive decoding with a KV cache — the inference half.

TPU-idiomatic incremental decode for the burn-in transformer: static
shapes throughout (the cache is allocated at ``max_seq`` and written with
``dynamic_update_slice``), the generation loop is one ``lax.scan`` over
positions (no Python control flow under jit), and attention over the cache
masks by position instead of re-slicing — so XLA compiles ONE step program
reused for every token.

The weights are the training checkpoints' (`models/burnin.py` layout);
teacher-forced decode reproduces ``burnin.forward`` logits exactly, which
is the correctness contract the tests pin.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_dra_driver_tpu.models.burnin import (
    ModelConfig,
    mlp_residual,
    qkv_proj,
    tied_logits,
)
from k8s_dra_driver_tpu.models.quant import mat as _mat


class KVCache(NamedTuple):
    """Per-layer stacked K/V: [L, B, max_seq, H, head_dim]."""

    k: jax.Array
    v: jax.Array


def init_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.float32
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _masked_attention(q, k, v, mask):
    """Shared attention core for BOTH decode paths: operands stay in the
    k/v (cache) dtype with f32 ACCUMULATION (``preferred_element_type``) —
    the MXU-native bf16-in/f32-out path, so a bf16 cache actually saves the
    bandwidth it exists to save.  One implementation so the numerics parity
    between batched prefill and sequential decode cannot drift.

    mask: broadcastable to [B, H, Q, K]; masked-out scores get -1e30."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(k.dtype),
            k,
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def _cached_attention(q, k_cache, v_cache, pos):
    """q: [B, 1, H, hd]; caches: [B, S_max, H, hd]; attend over positions
    <= pos (pos: [B] int32 — per ROW; the rest of the cache is masked, not
    sliced — static shapes keep the step program reusable)."""
    k_pos = jnp.arange(k_cache.shape[1])
    return _masked_attention(
        q, k_cache, v_cache, (k_pos[None, :] <= pos[:, None])[:, None, None, :]
    )


def decode_step(
    params, cache: KVCache, token: jax.Array, pos, *, cfg: ModelConfig, active=None
):
    """One incremental step.

    token: [B] int32 — the token at ``pos``;  pos: scalar int32 (whole
    batch at one depth — the sequential-decode case) or [B] int32 (per-row
    depth — the continuous-batching case, models/serve.py).  ``active``:
    optional [B] bool; inactive rows' cache writes become no-ops (their
    outputs are garbage the caller ignores).  One step implementation for
    BOTH decode paths so the numerics cannot drift.

    Returns (logits [B, V] f32 for position ``pos``, updated cache).
    """
    b = token.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    rows = jnp.arange(b)
    x = params["embed"][token][:, None, :] + params["pos_embed"][pos][:, None, :]

    new_k, new_v = cache.k, cache.v
    for li, p in enumerate(params["blocks"]):
        q, k, v = qkv_proj(x, p, cfg)  # [B, 1, H, hd] each
        k_new = k[:, 0].astype(new_k.dtype)
        v_new = v[:, 0].astype(new_v.dtype)
        if active is not None:
            gate = active[:, None, None]
            k_new = jnp.where(gate, k_new, new_k[li, rows, pos])
            v_new = jnp.where(gate, v_new, new_v[li, rows, pos])
        new_k = new_k.at[li, rows, pos].set(k_new)
        new_v = new_v.at[li, rows, pos].set(v_new)
        attn = _cached_attention(q, new_k[li], new_v[li], pos).reshape(b, 1, cfg.d_model)
        x = x + jnp.einsum("bsd,de->bse", attn, _mat(p["attn_out"]))
        x = mlp_residual(x, p)

    logits = tied_logits(x, params)
    return logits[:, 0], KVCache(k=new_k, v=new_v)


def greedy_decode(
    params, prompt: jax.Array, steps: int, cfg: ModelConfig,
    cache_dtype=jnp.float32, batch_prefill: bool = False,
) -> jax.Array:
    """Greedy continuation: prompt [B, P] int32 -> [B, P+steps].

    The temperature=0 case of :func:`sample_decode` (one shared scan body —
    the write-back indexing is the subtlest code here and must exist once).
    """
    return sample_decode(
        params, prompt, steps, cfg,
        key=jax.random.PRNGKey(0),  # unused at temperature 0
        temperature=0.0, cache_dtype=cache_dtype, batch_prefill=batch_prefill,
    )


def sample_decode(
    params,
    prompt: jax.Array,
    steps: int,
    cfg: ModelConfig,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    cache_dtype=jnp.float32,
    batch_prefill: bool = False,
) -> jax.Array:
    """Continuation: temperature + optional top-k filtering.

    ``temperature=0`` is exact greedy (argmax, rng unused); ``top_k=0``
    disables filtering.  Generation is one ``lax.scan`` of the incremental
    step; the prompt is consumed either inside the same scan (teacher
    forcing — one compiled program total) or, with ``batch_prefill=True``,
    by ONE parallel forward pass over the whole prompt (O(1) steps instead
    of O(prompt); the long-prompt serving path).  RNG keys are indexed by
    position, so both prefill modes sample identically (with a
    reduced-precision cache, up to accumulation order)."""
    b, p_len = prompt.shape
    total = p_len + steps
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt {p_len} + steps {steps} = {total} exceeds max_seq {cfg.max_seq}"
        )
    padded = jnp.concatenate(
        [prompt, jnp.zeros((b, steps), dtype=prompt.dtype)], axis=1
    )
    step_fn = functools.partial(decode_step, cfg=cfg)

    def pick(logits, k_rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        scaled = logits / jnp.float32(temperature)
        if top_k > 0:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        return jax.random.categorical(k_rng, scaled, axis=-1)

    def body(carry, inp):
        cache, tokens = carry
        pos, k_rng = inp
        token_in = jax.lax.dynamic_slice_in_dim(tokens, pos, 1, axis=1)[:, 0]
        logits, cache = step_fn(params, cache, token_in, pos)
        next_tok = pick(logits, k_rng).astype(tokens.dtype)
        write_pos = pos + 1
        keep_prompt = write_pos < p_len
        current = jax.lax.dynamic_slice_in_dim(tokens, write_pos, 1, axis=1)[:, 0]
        written = jnp.where(keep_prompt, current, next_tok)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, written[:, None], write_pos, axis=1
        )
        return (cache, tokens), None

    keys = jax.random.split(key, max(total - 1, 1))
    if batch_prefill:
        if steps == 0:
            return prompt
        cache, last_logits = prefill(
            params, prompt, cfg, max_seq=total, cache_dtype=cache_dtype
        )
        first = pick(last_logits, keys[p_len - 1]).astype(padded.dtype)
        padded = jax.lax.dynamic_update_slice_in_dim(
            padded, first[:, None], p_len, axis=1
        )
        positions = jnp.arange(p_len, total - 1)
        (_, tokens), _ = jax.lax.scan(
            body, (cache, padded), (positions, keys[p_len : total - 1])
        )
        return tokens
    cache = init_cache(cfg, b, total, dtype=cache_dtype)
    (_, tokens), _ = jax.lax.scan(
        body, (cache, padded), (jnp.arange(total - 1), keys[: total - 1])
    )
    return tokens


def _prefill_attention(q, k, v):
    """Causal attention over the prompt — the same ``_masked_attention``
    core as the sequential step, so the two prefill modes see identical
    numerics by construction."""
    s = q.shape[1]
    mask = jnp.tril(jnp.ones((s, s), dtype=bool))[None, None]
    return _masked_attention(q, k, v, mask)


def prefill(params, prompt: jax.Array, cfg: ModelConfig, max_seq: int,
            cache_dtype=jnp.float32):
    """Fill the KV cache for the whole prompt in ONE forward pass.

    Sequential per-token prefill wastes the MXU: the prompt is fully known,
    so each layer can project q/k/v for every position at once and run
    causal attention over the prompt (the training forward's shape), writing
    k/v into the cache as it goes — O(1) steps instead of O(prompt).
    Attention runs over the CACHE-dtype k/v (like the incremental step), so
    the two prefill modes agree up to accumulation order.

    Returns (cache, logits[B, V] for the LAST prompt position).
    """
    b, p_len = prompt.shape
    if p_len > max_seq:
        raise ValueError(f"prompt {p_len} exceeds max_seq {max_seq}")
    cache = init_cache(cfg, b, max_seq, dtype=cache_dtype)
    x = params["embed"][prompt] + params["pos_embed"][:p_len]

    new_k, new_v = cache.k, cache.v
    for li, p in enumerate(params["blocks"]):
        q, k, v = qkv_proj(x, p, cfg)  # [B, P, H, hd]
        k_c = k.astype(new_k.dtype)
        v_c = v.astype(new_v.dtype)
        new_k = new_k.at[li].set(
            jax.lax.dynamic_update_slice_in_dim(new_k[li], k_c, 0, axis=1)
        )
        new_v = new_v.at[li].set(
            jax.lax.dynamic_update_slice_in_dim(new_v[li], v_c, 0, axis=1)
        )
        attn = _prefill_attention(q, k_c, v_c).reshape(b, p_len, cfg.d_model)
        x = x + jnp.einsum("bsd,de->bse", attn, _mat(p["attn_out"]))
        x = mlp_residual(x, p)

    logits = tied_logits(x, params)[:, -1]
    return KVCache(k=new_k, v=new_v), logits
