"""Autoregressive decoding with a KV cache — the inference half.

TPU-idiomatic incremental decode for the burn-in transformer: static
shapes throughout (the cache is allocated at ``max_seq`` and written with
``dynamic_update_slice``), the generation loop is one ``lax.scan`` over
positions (no Python control flow under jit), and attention over the cache
masks by position instead of re-slicing — so XLA compiles ONE step program
reused for every token.

The weights are the training checkpoints' (`models/burnin.py` layout);
teacher-forced decode reproduces ``burnin.forward`` logits exactly, which
is the correctness contract the tests pin.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_dra_driver_tpu.models.burnin import (
    ModelConfig,
    mlp_residual,
    qkv_proj,
    tied_logits,
)


class KVCache(NamedTuple):
    """Per-layer stacked K/V: [L, B, max_seq, H, head_dim]."""

    k: jax.Array
    v: jax.Array


def init_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.float32
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.n_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _cached_attention(q, k_cache, v_cache, pos):
    """q: [B, 1, H, hd]; caches: [B, S_max, H, hd]; attend over
    positions <= pos (the rest of the cache is masked, not sliced —
    static shapes keep the step program reusable).

    Operands stay in the cache dtype with f32 ACCUMULATION
    (``preferred_element_type``) — the MXU-native bf16-in/f32-out path,
    so a bf16 cache actually saves the bandwidth it exists to save."""
    d = q.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk",
            q.astype(k_cache.dtype),
            k_cache,
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    k_pos = jnp.arange(k_cache.shape[1])
    scores = jnp.where(k_pos[None, None, None, :] <= pos, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        probs.astype(v_cache.dtype),
        v_cache,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def decode_step(params, cache: KVCache, token: jax.Array, pos, *, cfg: ModelConfig):
    """One incremental step.

    token: [B] int32 — the token at ``pos``;  pos: scalar int32.
    Returns (logits [B, V] f32 for position ``pos``, updated cache).
    """
    b = token.shape[0]
    x = params["embed"][token][:, None, :] + jax.lax.dynamic_slice_in_dim(
        params["pos_embed"], pos, 1, axis=0
    )  # [B, 1, D]

    new_k, new_v = cache.k, cache.v
    for li, p in enumerate(params["blocks"]):
        q, k, v = qkv_proj(x, p, cfg)  # [B, 1, H, hd] each
        new_k = new_k.at[li].set(
            jax.lax.dynamic_update_slice_in_dim(new_k[li], k.astype(new_k.dtype), pos, axis=1)
        )
        new_v = new_v.at[li].set(
            jax.lax.dynamic_update_slice_in_dim(new_v[li], v.astype(new_v.dtype), pos, axis=1)
        )
        attn = _cached_attention(q, new_k[li], new_v[li], pos).reshape(b, 1, cfg.d_model)
        x = x + jnp.einsum("bsd,de->bse", attn, p["attn_out"])
        x = mlp_residual(x, p)

    logits = tied_logits(x, params)
    return logits[:, 0], KVCache(k=new_k, v=new_v)


def greedy_decode(
    params, prompt: jax.Array, steps: int, cfg: ModelConfig, cache_dtype=jnp.float32
) -> jax.Array:
    """Greedy continuation: prompt [B, P] int32 -> [B, P+steps].

    The temperature=0 case of :func:`sample_decode` (one shared scan body —
    the write-back indexing is the subtlest code here and must exist once).
    """
    return sample_decode(
        params, prompt, steps, cfg,
        key=jax.random.PRNGKey(0),  # unused at temperature 0
        temperature=0.0, cache_dtype=cache_dtype,
    )


def sample_decode(
    params,
    prompt: jax.Array,
    steps: int,
    cfg: ModelConfig,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    cache_dtype=jnp.float32,
) -> jax.Array:
    """Continuation: temperature + optional top-k filtering.

    ``temperature=0`` is exact greedy (argmax, rng unused); ``top_k=0``
    disables filtering.  One fused scan covers prefill AND generation: at
    prompt positions the next input comes from the prompt (teacher
    forcing), afterwards from the sampler — a single compiled step, no
    separate prefill program."""
    b, p_len = prompt.shape
    total = p_len + steps
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt {p_len} + steps {steps} = {total} exceeds max_seq {cfg.max_seq}"
        )
    cache = init_cache(cfg, b, total, dtype=cache_dtype)
    padded = jnp.concatenate(
        [prompt, jnp.zeros((b, steps), dtype=prompt.dtype)], axis=1
    )
    step_fn = functools.partial(decode_step, cfg=cfg)

    def pick(logits, k_rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        scaled = logits / jnp.float32(temperature)
        if top_k > 0:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        return jax.random.categorical(k_rng, scaled, axis=-1)

    def body(carry, inp):
        cache, tokens = carry
        pos, k_rng = inp
        token_in = jax.lax.dynamic_slice_in_dim(tokens, pos, 1, axis=1)[:, 0]
        logits, cache = step_fn(params, cache, token_in, pos)
        next_tok = pick(logits, k_rng).astype(tokens.dtype)
        write_pos = pos + 1
        keep_prompt = write_pos < p_len
        current = jax.lax.dynamic_slice_in_dim(tokens, write_pos, 1, axis=1)[:, 0]
        written = jnp.where(keep_prompt, current, next_tok)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, written[:, None], write_pos, axis=1
        )
        return (cache, tokens), None

    keys = jax.random.split(key, total - 1)
    (_, tokens), _ = jax.lax.scan(body, (cache, padded), (jnp.arange(total - 1), keys))
    return tokens
