"""Autoregressive decoding with a KV cache — the inference half.

TPU-idiomatic incremental decode for the burn-in transformer: static
shapes throughout (the cache is allocated at ``max_seq`` and written with
``dynamic_update_slice``), the generation loop is one ``lax.scan`` over
positions (no Python control flow under jit), and attention over the cache
masks by position instead of re-slicing — so XLA compiles ONE step program
reused for every token.

The weights are the training checkpoints' (`models/burnin.py` layout);
teacher-forced decode reproduces ``burnin.forward`` logits exactly, which
is the correctness contract the tests pin.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_dra_driver_tpu.models.burnin import (
    ModelConfig,
    mlp_residual,
    qkv_proj,
    tied_logits,
)
from k8s_dra_driver_tpu.models.quant import matmul_last as _mm


class KVCache(NamedTuple):
    """Per-layer stacked K/V: [L, B, max_seq, Hkv, head_dim].  With GQA
    the head dim is ``cfg.kv_heads`` — the cache is the thing GQA shrinks
    (serving memory = slots x max_seq x Hkv x hd per layer)."""

    k: jax.Array
    v: jax.Array


def init_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.float32
) -> KVCache:
    shape = (cfg.n_layers, batch, max_seq, cfg.kv_heads, cfg.head_dim)
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _masked_attention(q, k, v, mask):
    """Shared attention core for BOTH decode paths: operands stay in the
    k/v (cache) dtype with f32 ACCUMULATION (``preferred_element_type``) —
    the MXU-native bf16-in/f32-out path, so a bf16 cache actually saves the
    bandwidth it exists to save.  One implementation so the numerics parity
    between batched prefill and sequential decode cannot drift.

    GQA: when q carries G = Hq/Hkv times more heads than k/v, the grouped
    einsum contracts each KV head against its G query heads directly — the
    narrow cache is never materialized wide (no jnp.repeat of [B,K,Hq,hd]
    on the bandwidth-bound decode path).

    mask: broadcastable to [B, H, Q, K] (the head axis broadcasts across
    grouped heads too); masked-out scores get -1e30."""
    d = q.shape[-1]
    hq, hkv = q.shape[2], k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.float32(d))
    if hq == hkv:
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                q.astype(k.dtype),
                k,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd",
            probs.astype(v.dtype),
            v,
            preferred_element_type=jnp.float32,
        )
        return out.astype(q.dtype)
    groups = hq // hkv
    b, s_q = q.shape[0], q.shape[1]
    qg = q.reshape(b, s_q, hkv, groups, d)
    scores = (
        jnp.einsum(
            "bqhgd,bkhd->bhgqk",
            qg.astype(k.dtype),
            k,
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    # Align the mask's head axis with the grouped [B, Hkv, G, Q, K] scores:
    # a broadcast head axis stays broadcast; a FULL per-query-head axis
    # (ALiBi-style) splits into its (kv-head, group) factors.  Anything
    # else is ambiguous — fail loudly rather than silently reinterpret a
    # per-KV-head mask as per-query-head.
    if mask.ndim == 4:
        if mask.shape[1] == 1:
            gmask = mask[:, :, None]
        elif mask.shape[1] == hq:
            gmask = mask.reshape(mask.shape[0], hkv, groups, *mask.shape[2:])
        else:
            raise ValueError(
                f"GQA mask head axis must be 1 or n_heads ({hq}), got {mask.shape[1]}"
            )
    elif mask.ndim == 3 and mask.shape[0] != 1:
        raise ValueError(
            f"ambiguous 3-d GQA mask with leading axis {mask.shape[0]}: "
            "pass [B, H, Q, K] (H = 1 or n_heads) or [Q, K]/[K]"
        )
    else:
        gmask = mask  # trailing [Q, K]/[K] axes broadcast against the scores
    scores = jnp.where(gmask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhgqk,bkhd->bqhgd",
        probs.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s_q, hq, d).astype(q.dtype)


def decode_chunk(
    params, cache: KVCache, tokens: jax.Array, pos0, *, cfg: ModelConfig,
    active=None, k_window: int | None = None, adapters=None,
):
    """THE incremental forward: score ``S`` known tokens in one pass.

    tokens: [B, S] int32 — the tokens at positions ``pos0 .. pos0+S-1``
    (``pos0``: scalar int32 — whole batch at one depth — or [B] int32,
    per-row depth).  Writes k/v for every chunk position into the cache,
    then attends each query over cache positions ``<=`` its own absolute
    position: within-chunk causality and the history mask fall out of one
    comparison, and the rest of the cache is masked, not sliced — static
    shapes keep the compiled program reusable.  ``active``: optional [B]
    bool; inactive rows' cache writes become no-ops (their outputs are
    garbage the caller ignores).  ``k_window``: optional STATIC upper
    bound on attended key positions — when the caller knows every query
    sits below it (prefill: queries 0..S-1 never see keys >= S), slicing
    the cache view to ``[:k_window]`` avoids paying attention FLOPs over
    the whole max_seq cache on the admission hot path.  ``adapters``:
    optional ``(bank, ids)`` — per-ROW LoRA from a serving bank
    (lora.stack_adapters): row r's projections gain its adapter's
    low-rank update via the shared qkv/mlp delta hooks.

    Returns (logits [B, S, V] f32 — one distribution per chunk position —
    and the updated cache).  This is the ONLY per-layer cache loop:
    `decode_step` is the S=1 view, `prefill` the pos0=0 view, and
    speculative verification (models/speculative.py) the general case — so
    the numerics across all decode paths cannot drift.
    """
    b, s = tokens.shape
    # A SCALAR pos0 (whole batch at one depth: sequential decode, prefill,
    # non-serving speculation) takes the dynamic-update-slice write path; a
    # [B] pos0 (continuous batching) needs the advanced-index scatter.
    # Same bytes either way, but on TPU the scatter write composing with
    # the attention read of the same carried cache makes XLA materialize
    # full-cache copies around every layer — measured 485µs vs 103µs per
    # b16/2k-ctx step on v5e — so the uniform case must never pay it.
    uniform = jnp.ndim(pos0) == 0
    start = jnp.asarray(pos0, jnp.int32)
    pos0 = jnp.broadcast_to(start, (b,))
    positions = pos0[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # [B, S]
    rows = jnp.arange(b)
    x = params["embed"][tokens]
    if not cfg.rope:
        x = x + params["pos_embed"][positions]

    k_limit = cache.k.shape[2] if k_window is None else k_window
    k_pos = jnp.arange(k_limit)
    # [B, 1(head), S(query), K]: key position <= query's absolute position
    mask = (k_pos[None, None, :] <= positions[:, :, None])[:, None]

    new_k, new_v = cache.k, cache.v
    for li, p in enumerate(params["blocks"]):
        delta = None
        if adapters is not None:
            from k8s_dra_driver_tpu.models import lora

            bank, ids = adapters
            delta = lora.adapter_delta(bank["blocks"][li], ids, bank["scale"])
        # q: [B, S, H, hd]; k/v: [B, S, Hkv, hd].  positions flow in so
        # RoPE rotates by ABSOLUTE position mid-stream (cache holds
        # rotated keys; history needs no re-rotation).
        q, k, v = qkv_proj(x, p, cfg, positions=positions, delta=delta)
        k_new = k.astype(new_k.dtype)
        v_new = v.astype(new_v.dtype)
        if uniform:
            if active is not None:
                gate = active[:, None, None, None]
                cur_k = jax.lax.dynamic_slice(
                    new_k, (li, 0, start, 0, 0), (1, b, s, *k_new.shape[2:])
                )[0]
                cur_v = jax.lax.dynamic_slice(
                    new_v, (li, 0, start, 0, 0), (1, b, s, *v_new.shape[2:])
                )[0]
                k_new = jnp.where(gate, k_new, cur_k)
                v_new = jnp.where(gate, v_new, cur_v)
            new_k = jax.lax.dynamic_update_slice(
                new_k, k_new[None], (li, 0, start, 0, 0)
            )
            new_v = jax.lax.dynamic_update_slice(
                new_v, v_new[None], (li, 0, start, 0, 0)
            )
        else:
            if active is not None:
                gate = active[:, None, None, None]
                k_new = jnp.where(gate, k_new, new_k[li][rows[:, None], positions])
                v_new = jnp.where(gate, v_new, new_v[li][rows[:, None], positions])
            new_k = new_k.at[li, rows[:, None], positions].set(k_new)
            new_v = new_v.at[li, rows[:, None], positions].set(v_new)
        attn = _masked_attention(
            q, new_k[li][:, :k_limit], new_v[li][:, :k_limit], mask
        ).reshape(b, s, cfg.d_model)
        x = x + _mm(attn, p["attn_out"])
        if delta is not None:
            x = x + delta("attn_out", attn)
        x = mlp_residual(x, p, delta=delta, top_k=cfg.moe_top_k)

    return tied_logits(x, params), KVCache(k=new_k, v=new_v)


def decode_step(
    params, cache: KVCache, token: jax.Array, pos, *, cfg: ModelConfig,
    active=None, adapters=None,
):
    """One incremental step — the S=1 view of :func:`decode_chunk`.

    token: [B] int32 — the token at ``pos``;  pos: scalar int32 (whole
    batch at one depth — the sequential-decode case) or [B] int32 (per-row
    depth — the continuous-batching case, models/serve.py).

    Returns (logits [B, V] f32 for position ``pos``, updated cache).
    """
    logits, cache = decode_chunk(
        params, cache, token[:, None], pos, cfg=cfg, active=active,
        adapters=adapters,
    )
    return logits[:, 0], cache


def advance_decode_state(next_tok, last, pos, active, stop_pos, eos_id):
    """On-device serving-state advance — the stop-mask half of the engines'
    pipelined decode loop (models/serve.py ``step_burst``).

    Folds the host retirement checks into the jitted step so a burst of K
    steps needs ONE device->host sync instead of K.  A row that just sampled
    ``next_tok`` at depth ``pos`` advances to ``pos + 1`` and stays active
    unless it hit ``eos_id`` or its precomputed ``stop_pos``
    (``prompt_len + max_tokens - 1``: the depth of the LAST token the
    request may commit, so ``new_pos >= stop_pos`` is exactly the host's
    ``n_gen >= max_tokens`` under the engine invariant
    ``pos == len(tokens) - 1``).  Inactive rows are frozen bit-for-bit.

    ``eos_id`` is traced (pass -1 for "no eos": token ids are >= 0, so it
    never matches).  Returns (new_last [B], new_pos [B], new_active [B]).
    """
    new_last = jnp.where(active, next_tok, last)
    new_pos = jnp.where(active, pos + 1, pos)
    done = active & ((next_tok == eos_id) | (new_pos >= stop_pos))
    return new_last, new_pos, active & ~done


def poison_rows(logits, poison):
    """Fault-injection hook for the serving engines: rows flagged in
    ``poison`` [B] bool get all-NaN logits — the deterministic stand-in for
    a numerically poisoned request (utils/faults.py ``nan_logits``).
    ``poison=None`` is the no-injector fast path (identical trace to
    before the hook existed)."""
    if poison is None:
        return logits
    return jnp.where(poison[:, None], jnp.nan, logits)


def finite_rows(logits):
    """[B] bool: every logit in the row is finite.  The on-device half of
    the poisoned-request quarantine detector — rows are independent in
    every engine program, so a non-finite row indicts exactly one request
    and the survivors' tokens in the same burst stay bit-equal."""
    return jnp.all(jnp.isfinite(logits), axis=-1)


def greedy_decode(
    params, prompt: jax.Array, steps: int, cfg: ModelConfig,
    cache_dtype=jnp.float32, batch_prefill: bool = False,
) -> jax.Array:
    """Greedy continuation: prompt [B, P] int32 -> [B, P+steps].

    The temperature=0 case of :func:`sample_decode` (one shared scan body —
    the write-back indexing is the subtlest code here and must exist once).
    """
    return sample_decode(
        params, prompt, steps, cfg,
        key=jax.random.PRNGKey(0),  # unused at temperature 0
        temperature=0.0, cache_dtype=cache_dtype, batch_prefill=batch_prefill,
    )


def sample_decode(
    params,
    prompt: jax.Array,
    steps: int,
    cfg: ModelConfig,
    key: jax.Array,
    temperature: float = 1.0,
    top_k: int = 0,
    cache_dtype=jnp.float32,
    batch_prefill: bool = False,
) -> jax.Array:
    """Continuation: temperature + optional top-k filtering.

    ``temperature=0`` is exact greedy (argmax, rng unused); ``top_k=0``
    disables filtering.  Generation is one ``lax.scan`` of the incremental
    step; the prompt is consumed either inside the same scan (teacher
    forcing — one compiled program total) or, with ``batch_prefill=True``,
    by ONE parallel forward pass over the whole prompt (O(1) steps instead
    of O(prompt); the long-prompt serving path).  RNG keys are indexed by
    position, so both prefill modes sample identically (with a
    reduced-precision cache, up to accumulation order)."""
    b, p_len = prompt.shape
    total = p_len + steps
    if total > cfg.max_seq:
        raise ValueError(
            f"prompt {p_len} + steps {steps} = {total} exceeds max_seq {cfg.max_seq}"
        )
    padded = jnp.concatenate(
        [prompt, jnp.zeros((b, steps), dtype=prompt.dtype)], axis=1
    )
    step_fn = functools.partial(decode_step, cfg=cfg)

    def pick(logits, k_rng):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        scaled = logits / jnp.float32(temperature)
        if top_k > 0:
            kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
        return jax.random.categorical(k_rng, scaled, axis=-1)

    def body(carry, inp):
        cache, tokens = carry
        pos, k_rng = inp
        token_in = jax.lax.dynamic_slice_in_dim(tokens, pos, 1, axis=1)[:, 0]
        logits, cache = step_fn(params, cache, token_in, pos)
        next_tok = pick(logits, k_rng).astype(tokens.dtype)
        write_pos = pos + 1
        keep_prompt = write_pos < p_len
        current = jax.lax.dynamic_slice_in_dim(tokens, write_pos, 1, axis=1)[:, 0]
        written = jnp.where(keep_prompt, current, next_tok)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, written[:, None], write_pos, axis=1
        )
        return (cache, tokens), None

    keys = jax.random.split(key, max(total - 1, 1))
    if batch_prefill:
        if steps == 0:
            return prompt
        cache, last_logits = prefill(
            params, prompt, cfg, max_seq=total, cache_dtype=cache_dtype
        )
        first = pick(last_logits, keys[p_len - 1]).astype(padded.dtype)
        padded = jax.lax.dynamic_update_slice_in_dim(
            padded, first[:, None], p_len, axis=1
        )
        positions = jnp.arange(p_len, total - 1)
        (_, tokens), _ = jax.lax.scan(
            body, (cache, padded), (positions, keys[p_len : total - 1])
        )
        return tokens
    cache = init_cache(cfg, b, total, dtype=cache_dtype)
    (_, tokens), _ = jax.lax.scan(
        body, (cache, padded), (jnp.arange(total - 1), keys[: total - 1])
    )
    return tokens


def prefill(params, prompt: jax.Array, cfg: ModelConfig, max_seq: int,
            cache_dtype=jnp.float32, adapters=None):
    """Fill the KV cache for the whole prompt in ONE forward pass.

    Sequential per-token prefill wastes the MXU: the prompt is fully known,
    so one :func:`decode_chunk` at ``pos0=0`` projects q/k/v for every
    position at once and runs causal attention over the prompt (the
    training forward's shape) — O(1) steps instead of O(prompt), and the
    same per-layer loop as the incremental step, so the two prefill modes
    agree by construction (attention runs over the CACHE-dtype k/v either
    way).

    Returns (cache, logits[B, V] for the LAST prompt position).
    """
    b, p_len = prompt.shape
    if p_len > max_seq:
        raise ValueError(f"prompt {p_len} exceeds max_seq {max_seq}")
    cache = init_cache(cfg, b, max_seq, dtype=cache_dtype)
    # k_window=p_len: prompt queries never see keys beyond the prompt, so
    # attention stays [B,H,P,P] (not [B,H,P,max_seq]) on the admission path.
    logits, cache = decode_chunk(
        params, cache, prompt, 0, cfg=cfg, k_window=p_len, adapters=adapters
    )
    return cache, logits[:, -1]
