"""Training-state checkpoint/resume for the data plane (orbax-backed).

The DRIVER's checkpointing (plugin/checkpoint.py) covers prepared-claim
state; this module covers the other half a training framework owes its
users: saving and restoring the JAX train state (params + optimizer state +
step) so a preempted slice job resumes where it left off.  Orbax handles
the sharded-array plumbing — on a mesh, arrays are saved/restored with
their shardings, each host writing its own shards (the standard multi-host
checkpoint pattern; works unchanged on a single device).

Usage:

    ckpt = TrainCheckpointer(dir, keep=3)
    step = ckpt.latest_step()             # None on a fresh run
    if step is not None:
        params, opt_state = ckpt.restore(step, like=(params, opt_state))
    ...
    ckpt.save(step, (params, opt_state))  # async-safe, atomic per step
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Optional

import jax


class TrainCheckpointer:
    """Thin, opinionated wrapper over orbax's CheckpointManager."""

    def __init__(self, directory: str | Path, keep: int = 3):
        import orbax.checkpoint as ocp

        self._dir = Path(directory).absolute()
        self._dir.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, wait: bool = True) -> None:
        """Persist ``state`` (any pytree of arrays) for ``step``."""
        import orbax.checkpoint as ocp

        self._manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._manager.wait_until_finished()

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore the pytree for ``step`` (default: latest).

        ``like``: an abstract/concrete pytree matching the saved structure;
        on a mesh, pass state built under the target shardings so arrays
        come back sharded the same way (resharding on restore is how a
        resumed job can even CHANGE its mesh shape)."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints under {self._dir}")
        if like is not None:
            abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, like)
            return self._manager.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        return self._manager.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._manager.all_steps())

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()
