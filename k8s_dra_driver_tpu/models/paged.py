"""Paged KV cache: block pool + allocator + decode over the block table.

The dense serving cache (models/serve.py) reserves ``n_slots x max_seq``
keys per layer forever — worst-case sized, mostly empty under ragged real
traffic.  This module stores KV in a shared pool of fixed-size blocks and
gives each sequence a *block table* (ops/paged_attention.py documents the
attention side).  What that buys, concretely:

* capacity is ``sum(ceil(len_i/bs))`` blocks, not ``n_slots x max_seq`` —
  a 32k-context request and thirty short chats share one pool;
* blocks allocate ON DEMAND as a sequence crosses a block boundary and
  free the moment it retires — admission control over a counter, not a
  worst-case reservation;
* per-step attention traffic follows actual lengths (the pallas kernel
  skips unused blocks' DMA), where the dense path reads max_seq per slot.

TPU-idiomatic split of labor: the ALLOCATOR is host-side numpy (a free
list is pointer-chasing — the wrong shape for XLA), while everything
per-token is jitted with static shapes — the pool, the table, and the
scatter of new k/v through ``table[row, pos//bs]`` never change shape.
Pool block 0 is reserved as the NULL block: inactive rows' writes land
there, so a freed-and-reassigned block can never be clobbered by a stale
inactive row (write-after-free via duplicate scatter indices is otherwise
silent corruption under XLA's unordered scatter).

Numerics contract (tested): paged greedy decode reproduces the dense
path's tokens exactly — paging changes residency, never results.

Reference parity note: the reference driver has no ML data plane
(SURVEY.md §2.11); consumer-side capability of the TPU framework.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models import decode
from k8s_dra_driver_tpu.models import quant
from k8s_dra_driver_tpu.models.burnin import (
    ModelConfig,
    mlp_residual,
    qkv_proj,
    tied_logits,
)
from k8s_dra_driver_tpu.models.quant import matmul_last as _mm
from k8s_dra_driver_tpu.models.telemetry import EngineTelemetry
from k8s_dra_driver_tpu.ops import paged_attention
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

NULL_BLOCK = 0  # reserved: inactive rows scatter here; never allocated

# Pool observability (the serving counters live in models/serve.py and are
# shared by both engine backends; this gauge is paged-specific).
_M_POOL_FREE = REGISTRY.gauge(
    "tpu_serve_kv_pool_free_blocks", "free KV pool blocks right now"
)
_M_PREEMPTIONS = REGISTRY.counter(
    "tpu_serve_preemptions_total",
    "requests evicted under pool pressure for later recompute-resume",
)
# Paged KV data plane (ARCHITECTURE.md "Paged KV data plane"): pool
# residency in BYTES, labeled by storage dtype, so capacity dashboards see
# the int8/int4 block win in the same unit HBM budgets are written in.
_M_KV_BYTES = REGISTRY.gauge(
    "tpu_serve_kv_bytes", "resident KV pool bytes (values + scales), by pool dtype"
)
_M_KV_DEQUANT = REGISTRY.counter(
    "tpu_serve_kv_dequant_total",
    "per-layer fused KV block dequantizations on the decode path",
)


class PagedKVCache(NamedTuple):
    """Per-layer stacked block pools: [L, n_blocks, Hkv, hd, block_size]
    (head-major and TRANSPOSED — positions on the minormost/lane axis, so
    the pallas kernel's manual DMA tiles are exact lane multiples and K
    arrives in VMEM already in K^T form; see
    ops/paged_attention.paged_window_attention).

    QUANTIZED pool mode: ``k``/``v`` may store int8 (or packed-int4 uint8,
    two lane positions per byte — the lane axis then holds
    ``block_size // 2`` bytes) with ONE f32 scale per (layer, block,
    kv-head) in ``k_scale``/``v_scale`` (``[L, n_blocks, Hkv]``; see
    models/quant.quantize_kv_blocks).  Quantized-ness is derived from the
    ARRAY dtype, never carried as pytree metadata, and the scale fields
    default to None so the float pool's pytree structure (and every
    sharded spec built against it) is unchanged."""

    k: jax.Array
    v: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @property
    def quantized(self) -> bool:
        return jnp.dtype(self.k.dtype) in (np.dtype(np.int8), np.dtype(np.uint8))

    @property
    def kv_dtype(self) -> str | None:
        """Storage-mode name ("int8"/"int4") or None for float pools."""
        if jnp.dtype(self.k.dtype) == np.dtype(np.int8):
            return "int8"
        if jnp.dtype(self.k.dtype) == np.dtype(np.uint8):
            return "int4"
        return None

    @property
    def block_size(self) -> int:
        bs = self.k.shape[4]
        # packed int4 holds two positions per lane byte
        return bs * 2 if jnp.dtype(self.k.dtype) == np.dtype(np.uint8) else bs

    @property
    def n_blocks(self) -> int:
        return self.k.shape[1]


def init_paged_cache(
    cfg: ModelConfig, n_blocks: int, block_size: int, dtype=jnp.float32,
    kv_dtype: str | None = None,
) -> PagedKVCache:
    if kv_dtype is None:
        shape = (cfg.n_layers, n_blocks, cfg.kv_heads, cfg.head_dim, block_size)
        return PagedKVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))
    quant.kv_dtype_bits(kv_dtype)  # validates the name
    if kv_dtype == "int4" and block_size % 2:
        raise ValueError(f"int4 pools need an even block_size, got {block_size}")
    lanes = block_size if kv_dtype == "int8" else block_size // 2
    shape = (cfg.n_layers, n_blocks, cfg.kv_heads, cfg.head_dim, lanes)
    sshape = (cfg.n_layers, n_blocks, cfg.kv_heads)
    if kv_dtype == "int8":
        zero = lambda: jnp.zeros(shape, jnp.int8)
    else:
        # packed zero: both nibbles hold biased 0 (+8) -> 0x88 per byte
        zero = lambda: jnp.full(shape, 0x88, jnp.uint8)
    return PagedKVCache(
        k=zero(), v=zero(),
        # scale 1.0 matches quantize_kv_blocks' all-zero-block convention
        k_scale=jnp.ones(sshape, jnp.float32),
        v_scale=jnp.ones(sshape, jnp.float32),
    )


def kv_block_bytes(cfg: ModelConfig, block_size: int, kv_dtype=jnp.float32) -> int:
    """Bytes ONE pool block costs across all layers, k + v, per-block
    scales included — the unit a ``pool_hbm_bytes`` budget divides by.
    ``kv_dtype`` is "int8"/"int4" or any float dtype."""
    l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    if isinstance(kv_dtype, str) and kv_dtype in quant.KV_DTYPES:
        bits = quant.kv_dtype_bits(kv_dtype)
        per_head = hd * block_size * bits // 8 + 4  # values + one f32 scale
    else:
        per_head = hd * block_size * jnp.dtype(kv_dtype).itemsize
    return 2 * l * hkv * per_head


def _quantized_block_write(pool, scale, li, bids, offs, vals, kv_dtype):
    """Insert one new [Hkv, hd] vector per row into its frontier block of
    a QUANTIZED pool at layer ``li``: gather block + scale, dequantize,
    lane-select the new value in at ``offs``, zero every lane PAST it,
    re-quantize, scatter block + scale back.

    The zero-tail is the determinism invariant: a recycled block's stale
    lane bytes must never fold into the fresh block's scale, so block
    content stays a pure function of the token history — which is what
    makes same-seed restore/handoff bit-exact and capture's clip to the
    used blocks lossless.  Duplicate ``bids`` only ever occur at the NULL
    block (inactive rows), which is never attended."""
    blk = pool[li, bids]                       # [B, Hkv, hd, lanes]
    sc = scale[li, bids]                       # [B, Hkv]
    deq = quant.dequant_kv_blocks(blk, sc)     # [B, Hkv, hd, bs] f32
    lane = jax.lax.broadcasted_iota(jnp.int32, deq.shape, 3)
    off = offs[:, None, None, None]
    deq = jnp.where(lane == off, vals.astype(jnp.float32)[..., None], deq)
    deq = jnp.where(lane <= off, deq, 0.0)
    qb, qs = quant.quantize_kv_blocks(deq, kv_dtype)
    return pool.at[li, bids].set(qb), scale.at[li, bids].set(qs)


class OutOfBlocks(RuntimeError):
    """Pool exhausted — admission control should have said no."""


class BlockAllocator:
    """Host-side refcounted free list over pool blocks 1..n_blocks-1 (0 is
    reserved).

    LIFO reuse on purpose: the hottest blocks (just freed, still resident
    in whatever cache hierarchy) are handed out first, and tests get
    deterministic tables.

    Refcounts are what make block-level PREFIX SHARING safe: a block holding
    a common prompt prefix is referenced by every slot using it (plus the
    prefix store); ``free`` drops one reference and the block returns to the
    pool only when the last holder lets go.
    """

    def __init__(self, n_blocks: int):
        if n_blocks < 2:
            raise ValueError(f"need >= 2 blocks (one is the null block), got {n_blocks}")
        self._free = list(range(n_blocks - 1, 0, -1))  # pop() -> lowest id first
        self._refs: dict[int, int] = {}
        self.n_blocks = n_blocks

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def refcount(self, block_id: int) -> int:
        return self._refs.get(block_id, 0)

    def alloc(self, n: int = 1) -> list[int]:
        if n > len(self._free):
            raise OutOfBlocks(
                f"requested {n} blocks, {len(self._free)} free of {self.n_blocks - 1}"
            )
        ids = [self._free.pop() for _ in range(n)]
        for i in ids:
            self._refs[i] = 1
        return ids

    def share(self, block_id: int) -> int:
        """Add a reference to a live block (prefix sharing)."""
        if self._refs.get(block_id, 0) < 1:
            raise ValueError(f"cannot share free block {block_id}")
        self._refs[block_id] += 1
        return block_id

    def free(self, ids) -> None:
        """Drop one reference per id; a block returns to the pool when its
        last reference drops.  Atomic: the WHOLE list is validated before
        any block is released, so a bad id mid-list (out of range, more
        drops than references) cannot leave the allocator and the caller's
        owned-list disagreeing about the earlier ids."""
        ids = [int(i) for i in ids]
        drops: dict[int, int] = {}
        for i in ids:
            if not 0 < i < self.n_blocks:
                raise ValueError(f"block id {i} out of range (null block is 0)")
            drops[i] = drops.get(i, 0) + 1
        for i, n in drops.items():
            if self._refs.get(i, 0) < n:
                raise ValueError(f"double free of block {i}")
        for i in ids:
            refs = self._refs[i]
            if refs == 1:
                del self._refs[i]
                self._free.append(i)
            else:
                self._refs[i] = refs - 1


def blocks_needed(tokens: int, block_size: int) -> int:
    return -(-tokens // block_size)


def default_attn_impl() -> str:
    """Pallas kernel on real TPU, gather-XLA elsewhere (CPU tests exercise
    the kernel explicitly via interpret=True)."""
    return "kernel" if jax.default_backend() == "tpu" else "xla"


@functools.partial(
    jax.jit, static_argnames=("cfg", "attn_impl", "interpret")
)
def paged_decode_step(
    params,
    cache: PagedKVCache,
    block_table: jax.Array,  # [B, max_blocks] i32
    token: jax.Array,        # [B] i32 — the token at ``pos``
    pos: jax.Array,          # [B] i32 per-row depth
    *,
    cfg: ModelConfig,
    active=None,             # [B] bool; inactive rows write the null block
    attn_impl: str = "xla",
    interpret: bool = False,
    adapters=None,
):
    """One incremental step over the paged cache — the paged mirror of
    :func:`decode.decode_step` (same qkv/mlp/logits helpers, so numerics
    cannot drift).  The S=1 view of :func:`paged_decode_chunk`; returns
    (logits [B, V] f32, updated cache)."""
    logits, cache = paged_decode_chunk(
        params, cache, block_table, token[:, None], pos, cfg=cfg,
        active=active, attn_impl=attn_impl, interpret=interpret,
        adapters=adapters,
    )
    return logits[:, 0], cache


@functools.partial(
    jax.jit, static_argnames=("cfg", "attn_impl", "interpret")
)
def paged_decode_chunk(
    params,
    cache: PagedKVCache,
    block_table: jax.Array,  # [B, max_blocks] i32
    window: jax.Array,       # [B, S] int32 — known tokens from each frontier
    pos: jax.Array,          # [B] i32 — window[:, j] sits at pos + j
    *,
    cfg: ModelConfig,
    active=None,
    attn_impl: str = "xla",
    interpret: bool = False,
    adapters=None,
):
    """Score ``S`` known tokens per row in ONE pass over the paged cache —
    the paged mirror of :func:`decode.decode_chunk` (per-layer: append the
    window's k/v to the pool, then windowed paged attention where query j
    attends positions <= pos + j).  This is what makes SPECULATIVE
    verification compose with paging: the verify window runs through the
    block table instead of a dense row.  Returns (logits [B, S, V] f32,
    updated cache).

    The kernel path FUSES the cache write into the attention kernel
    (ops/paged_attention.paged_append_attention): the pools thread through
    the pallas call aliased in-out, so the serving loop never copies them
    — the XLA scatter the fallback path uses forces a full pool copy
    around every custom call when both appear in one jitted step (the
    round-3 paged uniform-batch tax, eliminated in round 4)."""
    b, s = window.shape
    bs = cache.block_size
    rows = jnp.arange(b)
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
    positions = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]  # [B, S]

    x = params["embed"][window]
    if not cfg.rope:
        x = x + params["pos_embed"][positions]

    def layer_delta(li):
        if adapters is None:
            return None
        from k8s_dra_driver_tpu.models import lora

        bank, ids = adapters
        return lora.adapter_delta(bank["blocks"][li], ids, bank["scale"])

    if attn_impl == "kernel":
        new_k, new_v = cache.k, cache.v
        for li, p in enumerate(params["blocks"]):
            delta = layer_delta(li)
            q, k, v = qkv_proj(x, p, cfg, positions=positions, delta=delta)
            attn, new_k, new_v = paged_attention.paged_append_attention(
                q, k, v, new_k, new_v, block_table, pos, li,
                write_mask=active, interpret=interpret,
            )
            attn = attn.reshape(b, s, cfg.d_model)
            x = x + _mm(attn, p["attn_out"])
            if delta is not None:
                x = x + delta("attn_out", attn)
            x = mlp_residual(x, p, delta=delta, top_k=cfg.moe_top_k)
        return tied_logits(x, params), PagedKVCache(k=new_k, v=new_v)

    block_ids = block_table[rows[:, None], positions // bs]  # [B, S]
    offs = positions % bs
    if active is not None:
        # stale tables on inactive rows may point at REASSIGNED blocks —
        # divert their writes to the null block instead of gating values
        # (a duplicate-index scatter against the new owner is unordered)
        block_ids = jnp.where(active[:, None], block_ids, NULL_BLOCK)

    if cache.quantized:
        # Quantized pools: the per-token write is a gather -> dequant ->
        # insert -> ZERO-TAIL -> requant -> scatter of each touched block
        # (_quantized_block_write documents why the tail must zero), done
        # sequentially over the S window positions so two writes into the
        # same frontier block compose deterministically.  Attention then
        # reads the int-sized pool with dequant fused into the operand
        # load (paged_window_attention_xla_gqa).
        kv_dtype = cache.kv_dtype
        k_pool, v_pool = cache.k, cache.v
        k_sc, v_sc = cache.k_scale, cache.v_scale
        for li, p in enumerate(params["blocks"]):
            delta = layer_delta(li)
            q, k, v = qkv_proj(x, p, cfg, positions=positions, delta=delta)
            for si in range(s):
                k_pool, k_sc = _quantized_block_write(
                    k_pool, k_sc, li, block_ids[:, si], offs[:, si],
                    k[:, si], kv_dtype,
                )
                v_pool, v_sc = _quantized_block_write(
                    v_pool, v_sc, li, block_ids[:, si], offs[:, si],
                    v[:, si], kv_dtype,
                )
            attn = paged_attention.paged_window_attention_xla_gqa(
                q, k_pool[li], v_pool[li], block_table, pos,
                k_scale=k_sc[li], v_scale=v_sc[li],
            )
            attn = attn.reshape(b, s, cfg.d_model)
            x = x + _mm(attn, p["attn_out"])
            if delta is not None:
                x = x + delta("attn_out", attn)
            x = mlp_residual(x, p, delta=delta, top_k=cfg.moe_top_k)
        cache = PagedKVCache(k=k_pool, v=v_pool, k_scale=k_sc, v_scale=v_sc)
        return tied_logits(x, params), cache

    new_k, new_v = cache.k, cache.v
    for li, p in enumerate(params["blocks"]):
        delta = layer_delta(li)
        q, k, v = qkv_proj(x, p, cfg, positions=positions, delta=delta)
        new_k = new_k.at[li, block_ids, :, :, offs].set(k.astype(new_k.dtype))
        new_v = new_v.at[li, block_ids, :, :, offs].set(v.astype(new_v.dtype))
        cache = PagedKVCache(k=new_k, v=new_v)
        # the GQA block-layout gather path: bit-equal to
        # paged_window_attention_xla (tested) without its two materialized
        # sequence-major pool copies per layer
        attn = paged_attention.paged_window_attention_xla_gqa(
            q, cache.k[li], cache.v[li], block_table, pos
        )
        attn = attn.reshape(b, s, cfg.d_model)
        x = x + _mm(attn, p["attn_out"])
        if delta is not None:
            x = x + delta("attn_out", attn)
        x = mlp_residual(x, p, delta=delta, top_k=cfg.moe_top_k)

    return tied_logits(x, params), cache


def paged_prefill(
    params,
    prompt: jax.Array,  # [B, P]
    cache: PagedKVCache,
    block_table: jax.Array,  # [B, >= ceil(P/bs)] i32 — disjoint, owned rows
    adapters=None,
    *,
    cfg: ModelConfig,
):
    """Fill pool blocks for the whole prompt in ONE parallel forward.

    Runs the dense :func:`decode.prefill` over a prompt-sized scratch cache
    (P padded to whole blocks), then scatters each block stripe into the
    rows' pool blocks — admission pays one [B, P] pass, exactly like the
    dense engine, and the scratch is freed by XLA after the scatter.
    Returns (cache, logits [B, V] of the last prompt position).
    """
    b, p_len = prompt.shape
    bs = cache.block_size
    nb = blocks_needed(p_len, bs)
    p_pad = nb * bs
    dense, last_logits = decode.prefill(
        params, prompt, cfg, max_seq=p_pad,
        cache_dtype=jnp.float32 if cache.quantized else cache.k.dtype,
        adapters=adapters,
    )
    # [L, B, p_pad, Hkv, hd] -> blocks, then head-major TRANSPOSED to match
    # the pool: [L, B, nb, Hkv, hd, bs]
    l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
    kb = dense.k.reshape(l, b, nb, bs, hkv, hd).transpose(0, 1, 2, 4, 5, 3)
    vb = dense.v.reshape(l, b, nb, bs, hkv, hd).transpose(0, 1, 2, 4, 5, 3)
    ids = block_table[:, :nb]
    if cache.quantized:
        # whole-block quantization of the prefilled stripes (per-block
        # scales over (hd, bs)); the dense scratch stays f32 and is freed
        # by XLA after the scatter, exactly like the float path
        qk, ksc = quant.quantize_kv_blocks(kb, cache.kv_dtype)
        qv, vsc = quant.quantize_kv_blocks(vb, cache.kv_dtype)
        return (
            PagedKVCache(
                k=cache.k.at[:, ids].set(qk),
                v=cache.v.at[:, ids].set(qv),
                k_scale=cache.k_scale.at[:, ids].set(ksc),
                v_scale=cache.v_scale.at[:, ids].set(vsc),
            ),
            last_logits,
        )
    return (
        PagedKVCache(k=cache.k.at[:, ids].set(kb), v=cache.v.at[:, ids].set(vb)),
        last_logits,
    )


@functools.partial(jax.jit, static_argnames=("cfg", "chunk_len"))
def paged_prefill_chunk(
    params,
    prompt: jax.Array,       # [1, bucket] padded prompt
    cache: PagedKVCache,
    block_table_row: jax.Array,  # [1, >= ceil(bucket/bs)] — done ids first
    done_blocks: jax.Array,  # scalar i32 — leading FULL blocks already pooled
    *,
    cfg: ModelConfig,
    chunk_len: int,          # tokens to prefill this call
    adapters=None,
):
    """Incremental admission: gather the row's pooled blocks' k/v into a
    dense scratch row, run ONE `decode_chunk` over positions
    ``[done, done + chunk_len)`` (``pos0`` re-derives positions, RoPE
    included), and scatter only the chunk's blocks back into the pool.
    The done blocks are never re-written — whether they came from THIS
    request's earlier chunks (chunked prefill) or from the SHARED prefix
    store (block-level prefix cache): either way the attended bytes are
    the ones a full prefill produces, the dense engine's prefix-cache
    bit-equality argument (serve._prefill_suffix_into_slot).  Chunks must
    start block-aligned; the final chunk may end anywhere in the bucket.
    Returns the updated cache.

    ``done_blocks`` is a DYNAMIC operand on purpose: only ``chunk_len``
    shapes the program, so chunked admission compiles at most
    ``prefill_chunk_blocks`` variants EVER (the intermediate width plus
    the possible final widths), not one per (done, chunk) pair a long
    prompt walks through.  The price is static-shaped work over the whole
    prefill row (gather all ``mbp`` blocks, attend over the full bucket —
    stale bytes past the frontier are causally masked); buckets are small,
    recompiles are not.  The caller must ensure
    ``done_blocks*bs + chunk_len <= bucket`` (unverifiable on a traced
    scalar)."""
    b, bucket = prompt.shape
    bs = cache.block_size
    mbp = block_table_row.shape[1]
    p_pad = mbp * bs
    if chunk_len > bucket:
        raise ValueError(f"chunk_len {chunk_len} exceeds bucket {bucket}")
    done_blocks = jnp.asarray(done_blocks, jnp.int32)
    done_len = done_blocks * bs
    chunk_blocks = blocks_needed(chunk_len, bs)
    l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim

    # Gather the WHOLE prefill row (fixed width): blocks at or past the
    # frontier hold stale/zero bytes, but decode_chunk's causal mask keeps
    # any query from attending past its own position, so they are inert.
    ids = block_table_row[0, :mbp]
    # pool [L, N, Hkv, hd, bs] -> [L, mbp, Hkv, hd, bs] -> seq-major
    # (quantized pools dequantize the done blocks into the f32 scratch row
    # — the attended history is the dequantized one, same as decode)
    kb_g, vb_g = cache.k[:, ids], cache.v[:, ids]
    if cache.quantized:
        kb_g = quant.dequant_kv_blocks(kb_g, cache.k_scale[:, ids])
        vb_g = quant.dequant_kv_blocks(vb_g, cache.v_scale[:, ids])
    pre_k = kb_g.transpose(0, 1, 4, 2, 3).reshape(l, 1, p_pad, hkv, hd)
    pre_v = vb_g.transpose(0, 1, 4, 2, 3).reshape(l, 1, p_pad, hkv, hd)
    row = decode.KVCache(k=pre_k, v=pre_v)
    chunk = jax.lax.dynamic_slice(prompt, (0, done_len), (1, chunk_len))
    _, row = decode.decode_chunk(
        params, row, chunk, done_len, cfg=cfg, adapters=adapters
    )
    # scatter ONLY the chunk's blocks (done ones are pooled already)
    kb = row.k.reshape(l, b, mbp, bs, hkv, hd).transpose(0, 1, 2, 4, 5, 3)
    vb = row.v.reshape(l, b, mbp, bs, hkv, hd).transpose(0, 1, 2, 4, 5, 3)
    kb = jax.lax.dynamic_slice_in_dim(kb, done_blocks, chunk_blocks, axis=2)
    vb = jax.lax.dynamic_slice_in_dim(vb, done_blocks, chunk_blocks, axis=2)
    ids = jax.lax.dynamic_slice(block_table_row, (0, done_blocks), (1, chunk_blocks))
    if cache.quantized:
        qk, ksc = quant.quantize_kv_blocks(kb, cache.kv_dtype)
        qv, vsc = quant.quantize_kv_blocks(vb, cache.kv_dtype)
        return PagedKVCache(
            k=cache.k.at[:, ids].set(qk), v=cache.v.at[:, ids].set(qv),
            k_scale=cache.k_scale.at[:, ids].set(ksc),
            v_scale=cache.v_scale.at[:, ids].set(vsc),
        )
    return PagedKVCache(
        k=cache.k.at[:, ids].set(kb), v=cache.v.at[:, ids].set(vb)
    )


def paged_prefill_suffix(
    params, prompt, cache, block_table_row, *, cfg, cached_blocks,
    adapters=None,
):
    """Prefix-hit admission = one chunk covering everything after the
    shared prefix.  (``chunk_len`` still varies with the hit depth here —
    one compiled variant per distinct cached-block count, bounded by the
    prefill width and amortized across all requests sharing the store.)"""
    return paged_prefill_chunk(
        params, prompt, cache, block_table_row, cached_blocks, cfg=cfg,
        chunk_len=prompt.shape[1] - cached_blocks * cache.block_size,
        adapters=adapters,
    )


def _paged_spec_round(
    params, draft_params, cache: PagedKVCache, d_cache, table, last, pos,
    active, adapters=None,
    *, cfg: ModelConfig, gamma: int, attn_impl: str, interpret: bool,
):
    """ONE speculative round over the PAGED cache: the shared draft
    proposal (serve.draft_propose — dense draft cache) plus a paged verify
    chunk through the block table.  Same acceptance rule as everywhere
    (speculative.accept_advance).  Returns (target [B, gamma+1],
    advance [B], cache, d_cache)."""
    from k8s_dra_driver_tpu.models import serve
    from k8s_dra_driver_tpu.models.speculative import accept_advance

    d_cache, proposed = serve.draft_propose(
        draft_params, d_cache, last, pos, active, cfg=cfg, gamma=gamma
    )
    window = jnp.concatenate([last[:, None], proposed], axis=1)
    logits, cache = paged_decode_chunk(
        params, cache, table, window, pos, cfg=cfg, active=active,
        attn_impl=attn_impl, interpret=interpret, adapters=adapters,
    )
    target = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    _, advance = accept_advance(proposed, target, active)
    return target, advance, cache, d_cache


def _paged_step_all(
    params, cache, table, tokens, pos, active, temps, keys, adapters=None,
    poison=None,
    *, cfg: ModelConfig, top_k: int, attn_impl: str, interpret: bool,
):
    """One paged decode step for every slot at its own position + the
    shared sampling tail (serve.sample_next — ONE sampling implementation
    across backends, so the engines' bit-equality contract cannot drift).

    ``poison``/``bad`` mirror serve._step_all_slots: the optional
    fault-injection NaN mask in, the non-finite-row quarantine verdict out
    (decode.poison_rows / decode.finite_rows).  Rows stay independent —
    each row gathers only through its OWN table row, and the attention
    mask is select-based (jnp.where), so a NaN row never contaminates a
    survivor.  Returns (next_token [B], bad [B], cache)."""
    from k8s_dra_driver_tpu.models import serve

    logits, cache = paged_decode_step(
        params, cache, table, tokens, pos, cfg=cfg, active=active,
        attn_impl=attn_impl, interpret=interpret, adapters=adapters,
    )
    logits = decode.poison_rows(logits, poison)
    bad = ~decode.finite_rows(logits)
    return serve.sample_next(logits, pos, temps, keys, top_k=top_k), bad, cache


def _paged_pipelined_burst(
    params, cache, table, tokens, pos, active, temps, keys, stop_pos,
    adapters=None, poison=None,
    *, cfg: ModelConfig, top_k: int, attn_impl: str, interpret: bool,
    eos_id: int, k: int,
):
    """K FUSED pipelined paged steps in ONE jitted scan:
    :func:`_paged_step_all` plus the shared on-device stop-mask advance
    (decode.advance_decode_state) per iteration — the paged twin of
    serve._pipelined_burst, so ``step_burst`` pays one dispatch and one
    readback per K tokens.  Rows the host left inactive (stalled or free)
    stay frozen; rows that retire on device go inactive for the rest of
    the burst and their writes divert to the null block.  Returns
    (trace [3, K, B] i32 — token/active/bad planes STACKED on device so
    the host pays ONE readback for the whole burst, not one per plane —
    cache, last, pos, active); the bad plane/``poison`` are the quarantine
    detector and the injected-NaN mask, as in serve._pipelined_burst."""

    def body(carry, _):
        cache, last, pos, active = carry
        next_tok, bad, cache = _paged_step_all(
            params, cache, table, last, pos, active, temps, keys, adapters,
            poison,
            cfg=cfg, top_k=top_k, attn_impl=attn_impl, interpret=interpret,
        )
        new_last, new_pos, new_active = decode.advance_decode_state(
            next_tok, last, pos, active, stop_pos, eos_id
        )
        step_trace = jnp.stack(
            [next_tok, active.astype(jnp.int32), bad.astype(jnp.int32)]
        )
        return (cache, new_last, new_pos, new_active), step_trace

    (cache, last, pos, active), trace = jax.lax.scan(
        body, (cache, tokens, pos, active), None, length=k
    )
    # [K, 3, B] -> [3, K, B]: plane-major, so the host's single readback
    # slices token/active/bad views without touching the device again
    return trace.transpose(1, 0, 2), cache, last, pos, active


def _paged_first_token(
    params, cache, table, prompt, plen, slot, temp, key, adapters=None,
    *, cfg: ModelConfig, top_k: int, attn_impl: str, interpret: bool,
):
    """Admission tail: re-run the per-slot step at ``plen - 1`` over the
    freshly scattered prefill blocks (idempotent rewrite — same token, same
    position) and sample the first generated token, mirroring the dense
    engine's `_commit_row_and_first_token` so the streams agree."""
    n_slots = table.shape[0]
    last_tok = prompt[0, plen - 1]
    pos = jnp.full((n_slots,), plen - 1, jnp.int32)
    tok, _, cache = _paged_step_all(
        params, cache, table,
        jnp.full((n_slots,), last_tok, jnp.int32),
        pos,
        jnp.arange(n_slots) == slot,
        jnp.full((n_slots,), temp, jnp.float32),
        jnp.broadcast_to(key, (n_slots, *key.shape)),
        adapters,
        cfg=cfg, top_k=top_k, attn_impl=attn_impl, interpret=interpret,
    )
    return tok[slot], cache


def _paged_first_token_local(
    params, cache, table, prompt, plen, onehot, temp, key, adapters,
    *, cfg: ModelConfig, top_k: int, attn_impl: str, interpret: bool, axis: str,
):
    """shard_map body of the admission tail: like :func:`_paged_first_token`
    but the target slot arrives as a ONE-HOT over the (locally sharded) slot
    axis — a global slot index means nothing inside a shard — and the
    sampled token leaves via ``psum`` so every device returns the same
    replicated scalar.  Rows off this shard (onehot all-False) write the
    null block, the same inactive-row contract as the step program."""
    local = table.shape[0]
    last_tok = prompt[0, plen - 1]
    pos = jnp.full((local,), plen - 1, jnp.int32)
    tok, _, cache = _paged_step_all(
        params, cache, table,
        jnp.full((local,), last_tok, jnp.int32),
        pos, onehot,
        jnp.full((local,), temp, jnp.float32),
        jnp.broadcast_to(key, (local, *key.shape)),
        adapters,
        cfg=cfg, top_k=top_k, attn_impl=attn_impl, interpret=interpret,
    )
    tok_here = jnp.sum(jnp.where(onehot, tok, 0).astype(jnp.int32))
    return jax.lax.psum(tok_here, axis), cache


def _paged_prefill_masked(params, prompt, cache, row, flag, adapters, *, cfg):
    """shard_map body of whole-prompt admission: every device runs the same
    prefill program (SPMD), but only the pool shard whose ``flag`` is set
    keeps the block writes — everyone else's scatter is diverted to their
    local null block (the reserved scratch sink, never attended).  The
    returned last-position logits are replicated by construction (prompt
    and params are unvarying)."""
    row = jnp.where(flag[0], row, NULL_BLOCK)
    return paged_prefill(params, prompt, cache, row, adapters, cfg=cfg)


def _paged_prefill_chunk_masked(
    params, prompt, cache, row, done, flag, adapters, *, cfg, chunk_len,
):
    """shard_map body of chunked/suffix admission — same null-block
    diversion as :func:`_paged_prefill_masked`.  Off-shard devices also
    GATHER their own pool's bytes at the masked (null) ids for the done
    prefix, which is garbage — harmless, because everything they compute
    from it is scattered back into their null block."""
    row = jnp.where(flag[0], row, NULL_BLOCK)
    return paged_prefill_chunk(
        params, prompt, cache, row, done, cfg=cfg, chunk_len=chunk_len,
        adapters=adapters,
    )


def _prefill_draft_row_masked(draft_params, d_cache, prompt, plen, onehot, *, cfg):
    """shard_map body of the DRAFT-cache admission write: the dense draft
    cache shards its slot axis, so the one-row write becomes a select over
    the local rows (``onehot`` picks at most one).  Mirrors
    serve._prefill_draft_row's zero-tail contract."""
    from k8s_dra_driver_tpu.models import serve

    n_draft = d_cache.k.shape[0]
    row, _ = decode.prefill(
        draft_params, prompt, cfg, max_seq=d_cache.k.shape[2],
        cache_dtype=d_cache.k.dtype,
    )
    keep = (jnp.arange(d_cache.k.shape[2]) < plen)[None, :, None, None]
    new_k = jnp.where(keep, row.k[:n_draft, 0], 0).astype(d_cache.k.dtype)
    new_v = jnp.where(keep, row.v[:n_draft, 0], 0).astype(d_cache.v.dtype)
    sel = onehot[None, :, None, None, None]
    return serve.KVCache(
        k=jnp.where(sel, new_k[:, None], d_cache.k),
        v=jnp.where(sel, new_v[:, None], d_cache.v),
    )


@dataclasses.dataclass
class PagedServeEngine:
    """Continuous batching over the paged pool — the capacity-first engine.

    Same scheduling contract as `serve.ServeEngine` (submit/step/
    completions, per-request temperature, eos/max_tokens retirement, token
    streams bit-identical to the dense engine — tested) with the dense
    per-slot ``max_seq`` reservation replaced by pool accounting:

    * ``submit`` admits when a slot AND the prompt's blocks are free —
      capacity is ``n_blocks``, shared across ragged requests, not
      ``n_slots x max_seq``;
    * ``step`` allocates a block on demand when a slot's next write
      crosses a block boundary; if the pool is momentarily empty the slot
      STALLS for the step (stays resident, generates nothing) and resumes
      when a retirement frees blocks — backpressure instead of overrun;
    * retirement frees the slot's blocks immediately (table row reset to
      the null block).

    Not thread-safe; drive from one loop, like the dense engine.
    """

    params: dict
    cfg: ModelConfig
    n_slots: int = 8
    n_blocks: int = 65       # pool size incl. the reserved null block
    block_size: int = 16
    prompt_bucket: int = 64
    cache_dtype: object = jnp.float32
    # KV pool storage mode: None stores blocks in ``cache_dtype``; "int8"
    # / "int4" store quantized blocks + per-block scales (models/quant),
    # doubling / quadrupling the tokens a fixed HBM budget holds while
    # per-step pool reads stay int-sized (dequant fuses into the
    # attention operand load).  A FLOAT dtype name ("bfloat16") is also
    # accepted and routed to cache_dtype, so sweeps can treat kv_dtype as
    # one axis.  Quantized pools require attn_impl="xla" (the pallas
    # kernel's DMA pipeline moves raw blocks and has no dequant stage)
    # and an unsharded engine.
    kv_dtype: str | None = None
    # Size the pool by BYTES instead of blocks: when set, n_blocks is
    # derived as pool_hbm_bytes // kv_block_bytes(cfg, block_size,
    # kv_dtype or cache_dtype) — the equal-HBM-budget knob that makes the
    # int8/int4 capacity win visible to ``reservable_blocks`` and through
    # it to the disagg KV-demand ledger's admission headroom.
    pool_hbm_bytes: int | None = None
    eos_id: int | None = None
    top_k: int = 0
    attn_impl: str | None = None  # None = kernel on TPU, xla elsewhere
    interpret: bool = False
    # Pipelined decode (the dense engine's sync_interval, over the pool):
    # > 1 makes step_burst() dispatch up to K fused steps per host sync,
    # growing each participating slot's blocks for the WHOLE burst up
    # front (lookahead K-1).  Slots the pool cannot cover for a burst
    # stall for the burst; if NOBODY can, the burst degrades to the
    # one-step path so stall/preempt semantics match the sync loop.
    # Streams bit-equal sync_interval=1 (tested).
    sync_interval: int = 1
    # Block-level prefix caching: > 0 keeps up to this many FULL prompt
    # blocks in an LRU store and SHARES them (refcounted) across requests
    # whose prompts start with the same tokens — admission skips both the
    # blocks' memory and their prefill compute.  Paging generalizes the
    # dense engine's fixed-bucket prefix cache to any whole-block prefix.
    # Token streams are identical with caching on or off (tested).
    prefix_cache_blocks: int = 0
    # Chunked prefill (Sarathi-style): > 0 admits prompts incrementally,
    # at most this many BLOCKS of prefill per engine step, interleaved
    # with the decode batch — a long prompt no longer head-of-line blocks
    # every resident request's next token.  0 = whole-prompt admission in
    # submit().  Composes with the prefix store (shared blocks count as
    # already-done chunks).  Streams identical either way (tested).
    prefill_chunk_blocks: int = 0
    # Speculative serving over the PAGED cache: > 0 advances every active
    # greedy slot up to gamma+1 tokens per round — dense draft cache +
    # paged verify chunk through the block table.  Greedy-only; int8
    # self-draft default.  Composes with prefix sharing and chunked
    # admission (streams identical — tested).
    spec_gamma: int = 0
    draft_params: object = None
    # Per-request LoRA serving over the paged pool (S-LoRA shape): a
    # stacked bank (lora.stack_adapters); submit(..., adapter=k) applies
    # fine-tune k to that request inside the shared step.  Composes with
    # prefix sharing (the block store keys by adapter — adapted k/v never
    # leak across fine-tunes), chunked admission, preemption (the adapter
    # id parks and restores with the request), AND speculative rounds
    # (adapters apply to the paged verify chunk; the base-model draft
    # stays sound — the any-draft contract).
    adapter_bank: dict | None = None
    # Preemption (vLLM's recompute fallback): when the pool is exhausted
    # and EVERY resident slot stalls, evict the lowest-priority resumable
    # request — free its blocks, park its tokens + sampler state,
    # re-prefill it when the pool breathes — instead of deadlocking until
    # a retirement that may never come.  Resumption is bit-exact:
    # sampling keys fold by absolute position (serve.sample_next), so the
    # re-admitted stream continues exactly where it stopped (tested).  A
    # request grown past prompt_bucket can no longer re-prefill in one
    # pass and becomes unpreemptable; if every resident is, the wedge
    # error stands.  Default ON from measurement (bench
    # `serving_preemption` block): under a pool ~half the working set the
    # stall-only engine DEADLOCKS at 0 completed requests where
    # preemption completes the whole workload — vLLM ships recompute
    # preemption on by default for the same reason.  Set False only when
    # the pool is provisioned for the full resident worst case and the
    # admission-time wedge error is preferred over eviction latency.
    #
    # Per-request PRIORITY (submit(..., priority=k), higher = more
    # important) orders every scarcity decision: block growth under a
    # tight pool serves high-priority slots first (low-priority ones
    # stall), preemption evicts the lowest-priority resumable victim
    # (youngest within a tier), and re-admission drains high-priority
    # parked requests first (FIFO within a tier).  Priority never changes
    # WHAT a request generates — only when (tested).
    preempt_on_stall: bool = True
    # Data-parallel PAGED serving: shard the SLOT axis over a mesh axis —
    # each device owns n_slots/axis_size slots AND n_blocks/axis_size pool
    # blocks (its own null block included), so the hot step's
    # gather/scatter through the block table is LOCAL by construction
    # (jax.shard_map; no collectives in the decode loop).  Block-table
    # entries hold ids local to the owning shard; the host runs one
    # allocator and prefix store per shard and admits a request to a slot
    # whose shard has its blocks.  Every per-slot op is row-independent,
    # so the engine's bit-equality contract extends to the sharded engine
    # — paged + speculative + LoRA + prefix + chunked admission +
    # preemption all compose (tested).  Weights replicate (TP composes at
    # the params level, orthogonal to slot scheduling).  ``slot_axis`` may
    # be a TUPLE of axis names — ``("slice", "data")`` on a multislice
    # mesh shards slots and pool slice-major across every slice, and the
    # collective-free hot loop means nothing crosses DCN per step:
    # multislice paged serving for free (tested).
    mesh: object | None = None
    slot_axis: str | tuple = "data"
    # Data-plane fault injection (utils/faults.py engine hooks) — armed
    # programmatically or from DRA_FAULTS, consulted pre-dispatch each
    # step; None = no fault window.  The spec round does NOT consult the
    # injector (greedy verify has its own acceptance contract).
    fault_injector: object | None = None
    # Distinct quarantined requests before the engine declares itself
    # poisoned and wedges (serve._wedge_error).
    quarantine_limit: int = 3
    # Request-lifecycle telemetry (models/telemetry.py): traces, SLO
    # histograms, EngineStats.  Stamps only at the sync points the engine
    # already pays for — perf_smoke check_telemetry_overhead pins zero
    # added host syncs against a telemetry_enabled=False twin.
    telemetry_enabled: bool = True

    def __post_init__(self):
        cfg = self.cfg
        if self.prompt_bucket > cfg.max_seq:
            raise ValueError(
                f"prompt_bucket ({self.prompt_bucket}) exceeds max_seq ({cfg.max_seq})"
            )
        if self.attn_impl is None:
            self.attn_impl = default_attn_impl()
        if self.sync_interval < 1:
            raise ValueError(f"sync_interval must be >= 1, got {self.sync_interval}")
        if self.quarantine_limit < 1:
            raise ValueError(
                f"quarantine_limit must be >= 1, got {self.quarantine_limit}"
            )
        if self.fault_injector is None:
            from k8s_dra_driver_tpu.utils import faults

            raw = os.environ.get(faults.ENV_VAR, "")
            if raw:
                self.fault_injector = faults.FaultInjector.from_env(raw)
        # robustness state shared with the dense engine's helpers
        # (serve._early_retire / _quarantine_slot / _pump / _shed)
        self.quarantined: list[int] = []
        self.shed_count = 0
        self.last_shed = None
        self.pump_stats: dict = {}
        self._step_no = 0
        self._last_step_s = 0.0
        self.telemetry = EngineTelemetry(self, enabled=self.telemetry_enabled)
        if (
            self.attn_impl == "kernel"
            and not self.interpret
            and jax.default_backend() == "tpu"
            and self.block_size % 128
        ):
            # fail at construction, not deep inside the first submit()'s
            # trace: the TPU DMA kernel's copies must be lane-tile exact
            raise ValueError(
                f"block_size {self.block_size} needs % 128 == 0 for the TPU "
                "kernel path; use a 128-multiple or attn_impl='xla'"
            )
        bs = self.block_size
        # kv_dtype normalization: float NAMES route to cache_dtype (the
        # dense axis value sweeps pass), int modes stay and are guarded.
        if self.kv_dtype is not None and self.kv_dtype not in quant.KV_DTYPES:
            self.cache_dtype = jnp.zeros((), self.kv_dtype).dtype  # raises on junk
            self.kv_dtype = None
        if self.kv_dtype is not None:
            if self.attn_impl != "xla":
                raise ValueError(
                    f"kv_dtype={self.kv_dtype!r} needs attn_impl='xla' "
                    f"(got {self.attn_impl!r}): the pallas kernel moves raw "
                    "blocks with no dequant stage"
                )
            if self.mesh is not None:
                raise ValueError(
                    f"kv_dtype={self.kv_dtype!r} is single-shard only: "
                    "quantized pools carry scale arrays the sharded specs "
                    "do not cover"
                )
            if self.kv_dtype == "int4" and bs % 2:
                raise ValueError(
                    f"int4 pools need an even block_size, got {bs}"
                )
        if self.pool_hbm_bytes is not None:
            per_block = kv_block_bytes(
                cfg, bs, self.kv_dtype or self.cache_dtype
            )
            derived = self.pool_hbm_bytes // per_block
            if derived < 2:
                raise ValueError(
                    f"pool_hbm_bytes={self.pool_hbm_bytes} holds {derived} "
                    f"blocks of {per_block} bytes — need >= 2 (one is the "
                    "null block)"
                )
            self.n_blocks = int(derived)
        self._mb = blocks_needed(cfg.max_seq, bs)        # table width
        self._mbp = blocks_needed(self.prompt_bucket, bs)  # prefill width
        self._axis_size = 1
        if self.mesh is not None:
            from k8s_dra_driver_tpu.parallel.mesh import slot_axis_size

            ax_size = slot_axis_size(self.mesh, self.slot_axis)
            if self.n_slots % ax_size:
                raise ValueError(
                    f"n_slots ({self.n_slots}) must divide over "
                    f"{self.slot_axis!r} axis size {ax_size}"
                )
            if self.n_blocks % ax_size:
                raise ValueError(
                    f"n_blocks ({self.n_blocks}) must divide over "
                    f"{self.slot_axis!r} axis size {ax_size}"
                )
            if self.n_blocks // ax_size < 2:
                raise ValueError(
                    f"n_blocks ({self.n_blocks}) leaves < 2 blocks per shard "
                    f"(each shard reserves its own null block)"
                )
            self._axis_size = ax_size
        # Slots and pool blocks partition CONTIGUOUSLY over the axis (the
        # same split NamedSharding applies to the arrays), one allocator +
        # prefix store per shard; table entries are SHARD-LOCAL block ids.
        self._spg = self.n_slots // self._axis_size      # slots per shard
        self._npd = self.n_blocks // self._axis_size     # blocks per shard
        self._allocs = [BlockAllocator(self._npd) for _ in range(self._axis_size)]
        # Group-0 views (THE group when unsharded) — the names tests and
        # single-device tooling have always used.
        self._alloc = self._allocs[0]
        self._table_np = np.full((self.n_slots, self._mb), NULL_BLOCK, np.int32)
        self._owned: list[list[int]] = [[] for _ in range(self.n_slots)]
        self._prio: list[int] = [0] * self.n_slots
        self._slots: list = [None] * self.n_slots
        self._next_id = 0
        self._completions: list = []
        self.stalled_steps = 0  # slot-steps skipped waiting for a block
        # parked requests: priority-descending, FIFO within a tier
        # (_preempt_one keeps it sorted)
        self._preempted: list[dict] = []
        self.preempted_count = 0
        self._n_adapters = 0
        if self.mesh is None:
            self._cache = init_paged_cache(
                cfg, self.n_blocks, bs, dtype=self.cache_dtype,
                kv_dtype=self.kv_dtype,
            )
            self._table = jnp.asarray(self._table_np)
            self._last = jnp.zeros((self.n_slots,), jnp.int32)
            self._pos = jnp.zeros((self.n_slots,), jnp.int32)
            self._temps = jnp.zeros((self.n_slots,), jnp.float32)
            self._keys = jnp.stack([jax.random.PRNGKey(0)] * self.n_slots)
            self._adapter_ids = jnp.zeros((self.n_slots,), jnp.int32)
            self._stop_pos = jnp.zeros((self.n_slots,), jnp.int32)
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            slot_s = NamedSharding(self.mesh, P(self.slot_axis))
            pool_s = NamedSharding(self.mesh, P(None, self.slot_axis))
            # State is CREATED sharded (jit with out_shardings): the full
            # unsharded pool never materializes on one device — at serving
            # scale that intermediate is the peak-memory point (the dense
            # engine's own pattern, serve.ServeEngine.__post_init__).
            self._cache = jax.jit(
                lambda: init_paged_cache(cfg, self.n_blocks, bs, dtype=self.cache_dtype),
                out_shardings=PagedKVCache(k=pool_s, v=pool_s),
            )()
            make = jax.jit(
                lambda: (
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), jnp.float32),
                    jnp.stack([jax.random.PRNGKey(0)] * self.n_slots),
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), jnp.int32),
                ),
                out_shardings=(
                    slot_s, slot_s, slot_s, slot_s, slot_s, slot_s,
                ),
            )
            (
                self._last, self._pos, self._temps, self._keys,
                self._adapter_ids, self._stop_pos,
            ) = make()
            self.params = jax.device_put(
                self.params, NamedSharding(self.mesh, P())
            )
            self._table = None
            self._upload_table()
        if self.adapter_bank is not None:
            from k8s_dra_driver_tpu.models import lora

            self._n_adapters = lora.bank_size(self.adapter_bank)
        kw = dict(
            cfg=cfg, top_k=self.top_k,
            attn_impl=self.attn_impl, interpret=self.interpret,
        )
        # The per-token step DONATES the cache: the engine always reassigns
        # self._cache from the result, and without aliasing every step
        # would copy the whole pool — doubling peak HBM on the very
        # structure this engine sizes to fill it.  The ADMISSION fns do
        # NOT donate on purpose: a donated buffer is consumed at dispatch,
        # so a runtime failure (device OOM — most likely exactly at
        # admission) would leave self._cache deleted and wedge every
        # resident request; submit()'s block-recovery path relies on the
        # old cache surviving a failed call.  One pool copy per admission,
        # amortized over the request's whole token stream, buys that.
        self._chunk_fns: dict = {}  # mesh path: chunk_len -> compiled fn
        self.host_syncs = 0  # decode-loop readbacks (admission syncs excluded)
        self._pipe_kw = dict(**kw, eos_id=-1 if self.eos_id is None else self.eos_id)
        self._pipe_fns: dict = {}  # static burst length -> compiled scan
        if self.mesh is None:
            from k8s_dra_driver_tpu.models import serve

            self._step_fn = serve.shared_jit(
                _paged_step_all, donate_argnums=(1,), **kw
            )
            self._first_fn = serve.shared_jit(_paged_first_token, **kw)
            self._prefill_fn = serve.shared_jit(paged_prefill, cfg=cfg)
        else:
            from jax.sharding import PartitionSpec as P

            ax = self.slot_axis
            # shard_map specs: pool blocks + dense-cache slots shard the
            # same axis as the per-slot row vectors; params, prompts and
            # single-row admission adapters replicate.  The hot loop is
            # local by construction — no collective anywhere in the step
            # (the only psum in the engine is the admission tail's scalar
            # token broadcast).
            cache_p = PagedKVCache(k=P(None, ax), v=P(None, ax))
            row_p = P(ax)
            ad_p = (P(), P(ax)) if self.adapter_bank is not None else P()
            self._step_fn = jax.jit(
                jax.shard_map(
                    functools.partial(_paged_step_all, **kw),
                    mesh=self.mesh,
                    in_specs=(P(), cache_p, row_p, row_p, row_p, row_p,
                              row_p, row_p, ad_p, row_p),
                    out_specs=(row_p, row_p, cache_p),
                ),
                donate_argnums=(1,),
            )
            self._first_fn = jax.jit(
                jax.shard_map(
                    functools.partial(_paged_first_token_local, **kw, axis=ax),
                    mesh=self.mesh,
                    in_specs=(P(), cache_p, row_p, P(), P(), row_p, P(),
                              P(), ad_p),
                    out_specs=(P(), cache_p),
                )
            )
            self._prefill_fn = jax.jit(
                jax.shard_map(
                    functools.partial(_paged_prefill_masked, cfg=cfg),
                    mesh=self.mesh,
                    in_specs=(P(), P(), cache_p, P(), row_p, P()),
                    out_specs=(cache_p, P()),
                )
            )
        from collections import OrderedDict

        # prefix stores, one per pool shard (ONE store when unsharded):
        # tokens[0:(i+1)*bs] -> shard-local pool block id (holds one ref)
        self._prefix_stores: list[OrderedDict] = [
            OrderedDict() for _ in range(self._axis_size)
        ]
        self._prefix_store = self._prefix_stores[0]  # group-0 view
        self.prefix_hits = 0     # blocks reused across submits
        self.prefix_misses = 0   # storable blocks computed fresh
        # fleet prefix-cache tier hooks (models/fleet_prefix.py binds them):
        # on_prefix_store(tokens, n_tokens, adapter) after a block lands in
        # the store, on_prefix_evict(tokens, adapter) after an LRU drop —
        # host-only callbacks, no device work on either path
        self.on_prefix_store = None
        self.on_prefix_evict = None
        # chunked-admission queue: FIFO of dicts, head advances one chunk
        # per step() (see prefill_chunk_blocks)
        self._admitting: list[dict] = []
        # first-token retired entries (KV payloads) awaiting take_handoffs()
        self._handoffs: list = []
        # Multi-controller serving: when the mesh spans OS processes,
        # host readbacks of sharded state must allgather (every process
        # runs this same scheduler in lockstep — the standard JAX
        # multi-controller pattern, same as the dense engine).
        self._multiprocess = self.mesh is not None and any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat
        )
        self._d_cache = self._spec_fn = self._draft_prefill_fn = None
        if self.spec_gamma > 0:
            from k8s_dra_driver_tpu.models import serve

            self.draft_params, self._d_cache = serve.make_draft_state(
                self.params, self.draft_params, cfg, self.n_slots,
                self.cache_dtype,
            )
            if self.mesh is None:
                # pool + draft cache donate, like _step_fn
                self._spec_fn = serve.shared_jit(
                    _paged_spec_round, donate_argnums=(2, 3), cfg=cfg,
                    gamma=self.spec_gamma, attn_impl=self.attn_impl,
                    interpret=self.interpret,
                )
                self._draft_prefill_fn = serve.shared_jit(
                    serve._prefill_draft_row, cfg=cfg
                )
            else:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                ax = self.slot_axis
                cache_p = PagedKVCache(k=P(None, ax), v=P(None, ax))
                dkv_p = serve.KVCache(k=P(None, ax), v=P(None, ax))
                row_p = P(ax)
                ad_p = (P(), P(ax)) if self.adapter_bank is not None else P()
                # make_draft_state built the draft cache unsharded (a
                # transient the size of ONE dense cache — not the pool);
                # commit it to the slot sharding the round fns expect.
                dkv_s = NamedSharding(self.mesh, P(None, ax))
                self._d_cache = jax.device_put(self._d_cache, dkv_s)
                self.draft_params = jax.device_put(
                    self.draft_params, NamedSharding(self.mesh, P())
                )
                self._spec_fn = jax.jit(
                    jax.shard_map(
                        functools.partial(
                            _paged_spec_round, cfg=cfg, gamma=self.spec_gamma,
                            attn_impl=self.attn_impl, interpret=self.interpret,
                        ),
                        mesh=self.mesh,
                        in_specs=(P(), P(), cache_p, dkv_p, row_p, row_p,
                                  row_p, row_p, ad_p),
                        out_specs=(row_p, row_p, cache_p, dkv_p),
                    ),
                    donate_argnums=(2, 3),
                )
                self._draft_prefill_fn = jax.jit(
                    jax.shard_map(
                        functools.partial(_prefill_draft_row_masked, cfg=cfg),
                        mesh=self.mesh,
                        in_specs=(P(), dkv_p, P(), P(), row_p),
                        out_specs=dkv_p,
                    )
                )

    # -- public API --------------------------------------------------------
    @property
    def free_blocks(self) -> int:
        return sum(a.free_blocks for a in self._allocs)

    @property
    def reservable_blocks(self) -> int:
        """Total usable KV blocks (the reserved null block per shard
        excluded) — the capacity denominator the decode-side KV-demand
        admission ledger (models/disagg.py) budgets full-stream
        reservations against."""
        return sum(a.n_blocks - 1 for a in self._allocs)

    def free_slots(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def submit(
        self,
        prompt: list[int],
        max_tokens: int,
        temperature: float = 0.0,
        seed: int | None = None,
        adapter: int = 0,
        priority: int = 0,
        deadline: int | None = None,
        queued_at: float | None = None,
        handoff: bool = False,
    ) -> int:
        """Admit when a slot AND the prompt's blocks are available; raises
        RuntimeError otherwise (admission control is the caller's).
        ``adapter``: bank index for per-request LoRA (0 = the base).
        ``priority``: scarcity ranking (see the class docstring) — it
        orders stalls, evictions and re-admissions, never token content.
        ``deadline``: step budget — the request retires with status
        ``deadline_exceeded`` after this many generated tokens if eos has
        not landed first (the same stop-mask path as max_tokens, so a
        deadline costs no extra sync; blocks refund at retirement).
        ``handoff``: disaggregated-prefill mode — retire at first token
        with the KV payload queued for :meth:`take_handoffs` (slot AND
        blocks refund immediately; the decode-pool restorer delivers the
        Completion).  Composes with chunked admission: a chunked submit
        hands off when its final chunk activates."""
        from k8s_dra_driver_tpu.models import serve
        from k8s_dra_driver_tpu.models.serve import _Slot

        t_sub = self.telemetry.now()
        serve.check_submit(
            prompt, max_tokens, self.prompt_bucket, self.cfg.max_seq,
            spec_gamma=self.spec_gamma, temperature=temperature,
            deadline=deadline,
        )
        if adapter and self.adapter_bank is None:
            raise ValueError("adapter requested but the engine has no adapter_bank")
        if self.adapter_bank is not None and not 0 <= adapter < self._n_adapters:
            raise ValueError(
                f"adapter {adapter} out of range [0, {self._n_adapters})"
            )
        if self._preempted:
            # Parked requests hold no reservation, so an eager caller
            # re-filling every freed slot would starve them forever: give
            # them strict priority — drain what fits now, and refuse new
            # admissions while any remain parked.
            self._readmit()
            if self._preempted:
                raise RuntimeError(
                    "no free slot (preempted requests pending re-admission)"
                )
        free = [s for s in range(self.n_slots) if self._slots[s] is None]
        if not free:
            raise RuntimeError("no free slot")
        # padded prompt first: it is pure (no pool state), so a failure
        # here can never strand allocated blocks.  numpy ON PURPOSE: host
        # arrays shard cleanly into any jitted program from every process
        # of a multi-controller mesh; committed device arrays would not.
        padded = np.zeros((1, self.prompt_bucket), np.int32)
        padded[0, : len(prompt)] = prompt
        request_id = self._next_id
        # numpy key for the same multi-controller reason as ``padded``
        base_key = np.asarray(
            jax.random.PRNGKey(request_id if seed is None else seed)
        )

        # Prefix-store hit walk: the longest run of leading FULL blocks
        # whose token content is already pooled.  Two caps: (plen-1)//bs
        # keeps the block holding position plen-1 out of the store — the
        # admission tail rewrites that position through the STEP program,
        # whose bytes are not guaranteed bit-identical to the prefill's,
        # and a shared block must never be written at all (the dense
        # engine's strict `len(prompt) > prefix_bucket` for the same
        # reason); (bucket-1)//bs keeps the suffix chunk's width real.
        bs = self.block_size
        storable = min((len(prompt) - 1) // bs, (self.prompt_bucket - 1) // bs)
        # blocks for the prompt AND the first generated token's position;
        # shared prefix blocks satisfy the first `cached` entries
        need = blocks_needed(len(prompt) + 1, bs)
        picked = self._pick_slot(prompt, need, storable, adapter)
        if picked is None:
            raise RuntimeError(
                f"no free blocks ({need} needed, {self.free_blocks} free "
                f"across {self._axis_size} shard(s))"
            )
        slot, ids, cached = picked
        try:
            self.prefix_hits += cached
            if self.prefix_cache_blocks > 0 and storable > 0:
                serve._M_PREFIX.inc(outcome="hit" if cached else "miss")
            # ids set BEFORE the prefill: the admission tail's first-token
            # step already runs with this slot's adapter
            self._adapter_ids = self._adapter_ids.at[slot].set(adapter)
            self._prio[slot] = priority
            self._owned[slot] = ids
            self._table_np[slot, :] = NULL_BLOCK
            self._table_np[slot, :need] = ids
            self._upload_table()
        except BaseException:
            # the reservation half-landed (adapter upload / table upload can
            # raise): refund the picked blocks or nothing else ever will
            self._alloc_for(slot).free(ids)
            self._owned[slot] = []
            self._table_np[slot, :] = NULL_BLOCK
            raise

        if self.prefill_chunk_blocks > 0:
            # Chunked admission: reserve the slot now, prefill at most
            # prefill_chunk_blocks per step() so resident requests keep
            # generating while this prompt admits (shared prefix blocks
            # count as already-done chunks).
            self._next_id += 1
            self._slots[slot] = _Slot(
                request_id, list(prompt), len(prompt), max_tokens, deadline
            )
            self._admitting.append(
                dict(
                    slot=slot, prompt=list(prompt), padded=padded,
                    plen=len(prompt), done=cached, storable=storable,
                    cached=cached, temp=temperature, key=base_key,
                    adapter=adapter, handoff=handoff,
                )
            )
            # _M_REQUESTS counts at ACTIVATION (matching the non-chunked
            # path, which only counts successful admissions)
            # trace minted in the "admitting" state — admitted_at /
            # first_token_at stamp when the final chunk activates the slot
            self.telemetry.on_admit(
                request_id, prompt_len=len(prompt), max_tokens=max_tokens,
                deadline=deadline, adapter=adapter, submitted_at=t_sub,
                queued_at=queued_at, activated=False,
            )
            self._update_gauges()
            return request_id

        try:
            # Prefill writes ceil(bucket/bs) block stripes; entries past the
            # row's owned blocks are the null block (a scratch sink — those
            # positions are beyond plen+1 and re-written before ever attended).
            prefill_row = self._table_np[slot : slot + 1, : self._mbp].copy()
            row_ad = self._row_adapters(adapter)
            if cached:
                self._run_prefill_suffix(padded, prefill_row, cached, slot, row_ad)
            else:
                self._run_prefill(padded, prefill_row, slot, row_ad)
            self._store_prefix_blocks(prompt, slot, storable, cached, adapter)
            if self.spec_gamma > 0:
                # the draft model needs the prompt's k/v too (its layers)
                self._run_draft_prefill(padded, len(prompt), slot)
            first_tok = self._first_token(
                padded, len(prompt), slot, temperature, base_key
            )
        except BaseException:
            # a failed admission (device OOM, interrupt) must return its
            # blocks — the slot was never occupied, so nothing else will
            self._alloc_for(slot).free(self._owned[slot])
            self._owned[slot] = []
            self._table_np[slot, :] = NULL_BLOCK
            self._upload_table()
            raise
        self._next_id += 1
        st = _Slot(
            request_id, list(prompt) + [int(first_tok)], len(prompt),
            max_tokens, deadline,
        )
        self._slots[slot] = st
        self._last = self._last.at[slot].set(first_tok)
        self._pos = self._pos.at[slot].set(len(prompt))
        self._temps = self._temps.at[slot].set(temperature)
        self._keys = self._keys.at[slot].set(base_key)
        self._stop_pos = self._stop_pos.at[slot].set(
            len(prompt) + serve._slot_budget(st) - 1
        )
        serve._M_REQUESTS.inc()
        serve._M_TOKENS.inc()  # the admission step's first generated token
        # activation == first token here (the _first_token sync above), so
        # the trace's admission stamps piggyback on a sync already paid
        self.telemetry.on_admit(
            request_id, prompt_len=len(prompt), max_tokens=max_tokens,
            deadline=deadline, adapter=adapter, submitted_at=t_sub,
            queued_at=queued_at,
        )
        if handoff:
            self._handoff_retire(slot, temperature, base_key, adapter)
            return request_id
        self._retire(slot)  # max_tokens=1 or eos on the first token
        self._update_gauges()
        return request_id

    def _advance_admission(self) -> None:
        """Run at most ONE prefill chunk for the admission-queue head; on
        the final chunk, activate the slot (first token, sampler state)."""
        from k8s_dra_driver_tpu.models import serve

        if not self._admitting:
            return
        adm = self._admitting[0]
        slot = adm["slot"]
        bs = self.block_size
        # walk only the PROMPT's blocks (rounded up to a boundary, capped
        # at the bucket): padding past the prompt is never attended, so
        # prefilling it would only delay activation — first-token latency
        # must scale with the prompt, not the bucket
        real_end = min(blocks_needed(adm["plen"], bs) * bs, self.prompt_bucket)
        prefill_row = self._table_np[slot : slot + 1, : self._mbp].copy()
        try:
            row_ad = self._row_adapters(adm.get("adapter", 0))
            if real_end - adm["done"] * bs > self.prefill_chunk_blocks * bs:
                self._run_prefill_chunk(
                    adm["padded"], prefill_row, adm["done"],
                    self.prefill_chunk_blocks * bs, slot, row_ad,
                )
                adm["done"] += self.prefill_chunk_blocks
                self.telemetry.on_admission_chunk(self._slots[slot].request_id)
                return
            # final chunk (may be narrower than a whole number of blocks),
            # then activation
            chunk_len = real_end - adm["done"] * bs
            if chunk_len > 0:
                self._run_prefill_chunk(
                    adm["padded"], prefill_row, adm["done"], chunk_len,
                    slot, row_ad,
                )
                self.telemetry.on_admission_chunk(self._slots[slot].request_id)
            if self.spec_gamma > 0:
                self._run_draft_prefill(adm["padded"], adm["plen"], slot)
            first_tok = self._first_token(
                adm["padded"], adm["plen"], slot, adm["temp"], adm["key"]
            )
        except BaseException as exc:
            # failed mid-admission: release the reservation entirely AND
            # surface an errored Completion — the caller already holds the
            # request id, and without it a failed request is
            # indistinguishable from one still streaming
            self._admitting.pop(0)
            st = self._slots[slot]
            self._slots[slot] = None
            self._alloc_for(slot).free(self._owned[slot])
            self._owned[slot] = []
            self._table_np[slot, :] = NULL_BLOCK
            self._upload_table()
            serve._retire_parked(
                self, st, "error", f"{type(exc).__name__}: {exc}"
            )
            raise
        self._admitting.pop(0)
        serve._M_REQUESTS.inc()  # successful admission, like the sync path
        self._store_prefix_blocks(
            adm["prompt"], slot, adm["storable"], adm["cached"],
            adm.get("adapter", 0),
        )
        self._slots[slot].tokens.append(int(first_tok))
        self._last = self._last.at[slot].set(first_tok)
        self._pos = self._pos.at[slot].set(adm["plen"])
        self._temps = self._temps.at[slot].set(adm["temp"])
        self._keys = self._keys.at[slot].set(adm["key"])
        st = self._slots[slot]
        self._stop_pos = self._stop_pos.at[slot].set(
            st.prompt_len + serve._slot_budget(st) - 1
        )
        serve._M_TOKENS.inc()
        # the slot went live and its first token committed (the
        # _first_token sync above): the chunked admission ends HERE
        self.telemetry.on_activate(st.request_id)
        if adm.get("handoff"):
            self._handoff_retire(
                slot, adm["temp"], adm["key"], adm.get("adapter", 0)
            )
            return
        self._retire(slot)
        self._update_gauges()

    def _grow_active_slots(self, lookahead: int):
        """Ensure every resident, non-admitting slot owns blocks covering
        positions ``pos .. pos + lookahead`` (0 = the plain decode write;
        spec_gamma = the verify window; burst length - 1 for a pipelined
        burst).  Slots the pool cannot serve STALL for this step — they
        resume after a retirement frees blocks.
        Returns (active mask, table_dirty).

        The row depth is derived HOST-SIDE from the engine invariant
        ``pos[slot] == len(st.tokens) - 1`` (holds for every resident,
        non-admitting slot at every host-consistent point: admission sets
        both, each committed token appends one and advances pos by one —
        spec clips only when it also retires — and readmit restores both).
        Reading ``self._pos`` back from the device here would serialize
        the loop against the device ONCE PER STEP — the exact per-token
        sync the pipelined decode loop exists to remove."""
        from k8s_dra_driver_tpu.models import serve

        admitting = {a["slot"] for a in self._admitting}
        active = np.zeros((self.n_slots,), bool)
        table_dirty = False
        # Scarcity order: high priority grows first (so a tight pool
        # stalls the LOW-priority slots), older request first within a
        # tier.  Deterministic for multi-controller lockstep.
        order = sorted(
            range(self.n_slots),
            key=lambda s: (
                -self._prio[s],
                self._slots[s].request_id if self._slots[s] else 0,
            ),
        )
        for slot in order:
            st = self._slots[slot]
            if st is None or slot in admitting:
                continue
            # Clamp to the slot's own remaining stream: a fixed-shape burst
            # asks for lookahead K-1 even when the slot retires sooner, and
            # blocks it will never write must not stall a tight pool.
            # _slot_budget folds the deadline in — a deadline-bound slot
            # never grows blocks past the step it retires at.
            remaining = st.prompt_len + serve._slot_budget(st) - len(st.tokens)
            ahead = min(lookahead, max(remaining - 1, 0))
            needed = (len(st.tokens) - 1 + ahead) // self.block_size + 1
            grew = True
            while len(self._owned[slot]) < needed:
                try:
                    (new_id,) = self._alloc_for(slot).alloc(1)
                except OutOfBlocks:
                    self.stalled_steps += 1  # resumes after a retirement
                    grew = False
                    break
                self._owned[slot].append(new_id)
                self._table_np[slot, len(self._owned[slot]) - 1] = new_id
                table_dirty = True
            if grew:
                active[slot] = True
        return active, table_dirty

    def _preempt_one(self, group: int | None = None) -> bool:
        """Evict the lowest-PRIORITY resumable resident request (youngest
        — highest request id — within a tier, still short enough to
        re-prefill): free its blocks, park its tokens and sampler state on
        the re-admission queue.  ``group`` restricts victims to one pool
        shard (evicting elsewhere cannot free the wedged shard's blocks).
        Returns whether a victim was evicted."""
        admitting = {a["slot"] for a in self._admitting}
        victim, vslot = None, -1
        for slot, st in enumerate(self._slots):
            if st is None or slot in admitting:
                continue
            if group is not None and self._group(slot) != group:
                continue
            if len(st.tokens) + 1 > self.prompt_bucket:
                continue  # grown past one-pass re-prefill: not resumable
            if victim is None or (
                (self._prio[slot], -st.request_id)
                < (self._prio[vslot], -victim.request_id)
            ):
                victim, vslot = st, slot
        if victim is None:
            return False
        temps = self._readback(self._temps)
        ads = self._readback(self._adapter_ids)
        keys = self._readback(self._keys)
        self._preempted.append(
            dict(
                st=victim, temp=float(temps[vslot]), key=keys[vslot],
                adapter=int(ads[vslot]), priority=self._prio[vslot],
            )
        )
        # re-admission drains high priority first, FIFO within a tier
        # (stable sort over park order)
        self._preempted.sort(key=lambda r: -r.get("priority", 0))
        self._slots[vslot] = None
        self._alloc_for(vslot).free(self._owned[vslot])
        self._owned[vslot] = []
        self._table_np[vslot, :] = NULL_BLOCK
        # table upload deferred: the caller (_grow_or_preempt) batches the
        # device transfer with the growth pass's own table_dirty
        self.preempted_count += 1
        _M_PREEMPTIONS.inc()
        self.telemetry.on_event(victim.request_id, "preempt")
        return True

    def _readmit(self) -> None:
        """Re-prefill parked requests (priority-first, FIFO within a
        tier — the queue order _preempt_one maintains) while a slot AND
        their blocks are free.  The parked token list (prompt + generated so far)
        re-admits AS the prompt; the next step then generates the next
        token at the same position with the same fold-by-position sampler
        key — the stream continues bit-exactly.  The prefix store is
        consulted like any admission (hits can only ever cover ORIGINAL
        prompt blocks — generated positions are never stored — so a hot
        shared prefix is not re-prefilled on every preempt cycle); fresh
        blocks from a resume are not stored back (conservative: the walk
        that decides storability ran at first admission)."""
        from k8s_dra_driver_tpu.models import serve

        while self._preempted:
            r = self._preempted[0]
            st = r["st"]
            tokens = st.tokens
            bs = self.block_size
            adapter = r.get("adapter", 0)
            storable = min(
                (len(tokens) - 1) // bs, (self.prompt_bucket - 1) // bs
            )
            need = blocks_needed(len(tokens) + 1, bs)
            picked = self._pick_slot(tokens, need, storable, adapter)
            if picked is None:
                return  # stays parked (FIFO head blocks the queue)
            slot, ids, cached = picked
            try:
                self._owned[slot] = ids
                self._table_np[slot, :] = NULL_BLOCK
                self._table_np[slot, :need] = ids
                self._upload_table()
                padded = np.zeros((1, self.prompt_bucket), np.int32)
                padded[0, : len(tokens)] = tokens
                prefill_row = self._table_np[slot : slot + 1, : self._mbp].copy()
                self._adapter_ids = self._adapter_ids.at[slot].set(adapter)
                self._prio[slot] = r.get("priority", 0)
                row_ad = self._row_adapters(adapter)
                if cached:
                    self._run_prefill_suffix(
                        padded, prefill_row, cached, slot, row_ad
                    )
                else:
                    self._run_prefill(padded, prefill_row, slot, row_ad)
                if self.spec_gamma > 0:
                    self._run_draft_prefill(padded, len(tokens), slot)
            except BaseException as exc:
                # failed re-admission (table/adapter upload or re-prefill):
                # release the reservation AND surface an errored Completion —
                # the caller holds the request id, and a silently re-parked
                # request is indistinguishable from one still streaming (same
                # contract as the chunked-admission failure path)
                self._alloc_for(slot).free(ids)
                self._owned[slot] = []
                self._table_np[slot, :] = NULL_BLOCK
                self._upload_table()
                self._preempted.pop(0)
                serve._retire_parked(
                    self, st, "error", f"{type(exc).__name__}: {exc}"
                )
                raise
            self._preempted.pop(0)
            self._slots[slot] = st
            self._last = self._last.at[slot].set(tokens[-1])
            self._pos = self._pos.at[slot].set(len(tokens) - 1)
            self._temps = self._temps.at[slot].set(r["temp"])
            self._keys = self._keys.at[slot].set(r["key"])
            # stop depth is a function of the ORIGINAL prompt_len and
            # step budget (max_tokens clamped by any deadline) — it
            # survives preemption unchanged
            self._stop_pos = self._stop_pos.at[slot].set(
                st.prompt_len + serve._slot_budget(st) - 1
            )
            self.telemetry.on_event(st.request_id, "readmit")
            self._update_gauges()

    def _grow_or_preempt(self, lookahead: int):
        """_grow_active_slots, escalating to preemption when a SHARD's
        whole resident set stalls with nothing admitting there
        (preempt_on_stall).  Per-shard on purpose: a wedged shard's pool
        only breathes through its own retirements, which a fully stalled
        set never produces — no matter how busy the other shards are
        (with one shard, this is exactly the old whole-engine rule).
        Evictions mark the table dirty; the device upload batches with
        the caller's."""
        active, table_dirty = self._grow_active_slots(lookahead)
        if self.preempt_on_stall:
            admitting_groups = {
                self._group(a["slot"]) for a in self._admitting
            }
            evicted = False
            for g in range(self._axis_size):
                if g in admitting_groups:
                    continue  # the admitting head will activate and retire
                slots_g = range(g * self._spg, (g + 1) * self._spg)
                while True:
                    resident = [
                        s for s in slots_g if self._slots[s] is not None
                    ]
                    if not resident or any(active[s] for s in resident):
                        break
                    if not self._preempt_one(group=g):
                        break
                    evicted = True
                    table_dirty = True  # victim rows were NULLed host-side
                    active, dirty2 = self._grow_active_slots(lookahead)
                    table_dirty = table_dirty or dirty2
            if evicted:
                self._update_gauges()
        return active, table_dirty

    def _spec_step(self) -> int:
        """One speculative ROUND over the paged pool: grow each active
        slot's blocks to cover the verify window (pos .. pos+gamma), stall
        rows the pool cannot serve, run the round, commit clipped tokens
        (the dense engine's _spec_step contract, plus pool accounting)."""
        from k8s_dra_driver_tpu.models import serve

        active, table_dirty = self._grow_or_preempt(lookahead=self.spec_gamma)
        if not active.any():
            return 0
        if table_dirty:
            self._upload_table()
        self.telemetry.burst_begin(self.spec_gamma + 1, self._step_no)
        active_j = self._slot_device(active)
        target, advance, self._cache, self._d_cache = self._spec_fn(
            self.params, self.draft_params, self._cache, self._d_cache,
            self._table, self._last, self._pos, active_j, self._adapters(),
        )
        rows = jnp.arange(self.n_slots)
        new_last = target[rows, jnp.maximum(advance - 1, 0)]
        self._last = jnp.where(active_j, new_last, self._last)
        self._pos = self._pos + advance  # advance is already 0 when inactive
        tgt = self._readback(target)
        adv = self._readback(advance)
        self.host_syncs += 1
        serve._M_HOST_SYNCS.inc()
        committed = 0
        for slot, st in enumerate(self._slots):
            if st is None or not active[slot]:
                continue
            before = len(st.tokens)
            for j in range(int(adv[slot])):
                st.tokens.append(int(tgt[slot, j]))
                committed += 1
                n_gen = len(st.tokens) - st.prompt_len
                hit_eos = self.eos_id is not None and st.tokens[-1] == self.eos_id
                if n_gen >= serve._slot_budget(st) or hit_eos:
                    break
            self.telemetry.on_commit(st.request_id, len(st.tokens) - before)
            self._retire(slot)
        self.telemetry.burst_end(int(active.sum()))
        serve._M_TOKENS.inc(committed)
        self._update_gauges()
        return int(active.sum())

    def step(self) -> int:
        """Advance every active, non-stalled slot one token (and the
        admission-queue head by one prefill chunk, and re-admit preempted
        requests the pool can now hold); returns the number of slots
        stepped."""
        from k8s_dra_driver_tpu.models import serve

        t0 = time.perf_counter()
        self._readmit()
        self._advance_admission()
        if self.spec_gamma > 0:
            return self._spec_step()
        self._step_no += 1
        poison, quarantined = serve._inject_step_faults(self)
        active, table_dirty = self._grow_or_preempt(lookahead=0)
        if not active.any():
            if table_dirty:
                self._upload_table()
            # quarantining IS progress — the wedge detector must not
            # mistake a fully quarantined step for a stall
            return quarantined
        if table_dirty:
            self._upload_table()
        self.telemetry.burst_begin(1, self._step_no)
        active_j = self._slot_device(active)
        next_tok, bad, self._cache = self._step_fn(
            self.params, self._cache, self._table, self._last, self._pos,
            active_j, self._temps, self._keys, self._adapters(),
            self._slot_device(poison),
        )
        self._last = jnp.where(active_j, next_tok, self._last)
        self._pos = jnp.where(active_j, self._pos + 1, self._pos)
        toks = self._readback(next_tok).tolist()
        bads = self._readback(bad)
        self.host_syncs += 1
        serve._M_HOST_SYNCS.inc()
        if self._cache.quantized:
            _M_KV_DEQUANT.inc(self.cfg.n_layers)
        committed = 0
        for slot, st in enumerate(self._slots):
            if st is None or not active[slot]:
                continue
            if bads[slot]:
                # rows are independent: dropping the poisoned commit IS
                # the replay — the survivors' tokens are already bit-equal
                # to a step that never contained this row
                serve._quarantine_slot(
                    self, slot, "nan_logits",
                    "non-finite logits in decode step",
                )
                continue
            st.tokens.append(toks[slot])
            self.telemetry.on_commit(st.request_id)
            committed += 1
            self._retire(slot)
        self.telemetry.burst_end(int(active.sum()))
        serve._M_TOKENS.inc(committed)
        self._update_gauges()
        self._last_step_s = time.perf_counter() - t0
        serve._M_STEP_LATENCY.observe(self._last_step_s)
        return int(active.sum())

    def step_burst(self) -> int:
        """Advance every participating slot up to ``sync_interval`` tokens
        with ONE device->host sync — the paged twin of
        serve.ServeEngine.step_burst; returns #slots stepped.

        Admission work (readmit, one prefill chunk) runs once per BURST
        instead of once per step — a scheduling change only, streams are
        unchanged.  Block growth covers the whole burst up front
        (``lookahead = K - 1``, clamped per slot to its remaining stream);
        a slot the pool cannot cover for K steps stalls for the burst, and
        if NO slot can, the burst degrades to lookahead 0 with K = 1 so
        the stall/preempt/wedge semantics are exactly the synchronous
        loop's (liveness: whenever step() could progress, step_burst()
        progresses).  K is otherwise always ``sync_interval`` — the burst
        is ONE compiled scan (:func:`_paged_pipelined_burst`), and a fixed
        shape keeps it one trace.  Rows that retire mid-burst go inactive
        ON DEVICE (stop masks); their blocks free at the host replay —
        held at most K - 1 extra steps."""
        if self.sync_interval <= 1 or self.spec_gamma > 0:
            return self.step()
        from k8s_dra_driver_tpu.models import serve
        from k8s_dra_driver_tpu.utils.watchdog import WATCHDOG

        t0 = time.perf_counter()
        self._readmit()
        self._advance_admission()
        self._step_no += 1
        poison, quarantined = serve._inject_step_faults(self)
        admitting = {a["slot"] for a in self._admitting}
        if not any(
            st is not None and slot not in admitting
            for slot, st in enumerate(self._slots)
        ):
            return quarantined
        k = self.sync_interval
        active, table_dirty = self._grow_or_preempt(lookahead=k - 1)
        if not active.any() and k > 1:
            # tight pool: burst-length lookahead stalls everyone; take the
            # sync loop's one-step growth instead of wedging
            k = 1
            active, dirty2 = self._grow_or_preempt(lookahead=0)
            table_dirty = table_dirty or dirty2
        if not active.any():
            if table_dirty:
                self._upload_table()
            return quarantined
        if table_dirty:
            self._upload_table()
        active_j = self._slot_device(active)

        self.telemetry.burst_begin(k, self._step_no)
        with WATCHDOG.guard("serve.paged_step_burst"):
            (
                trace, self._cache,
                self._last, self._pos, active_j,
            ) = self._burst_fn(k)(
                self.params, self._cache, self._table, self._last,
                self._pos, active_j, self._temps, self._keys,
                self._stop_pos, self._adapters(), self._slot_device(poison),
            )
            # the burst's ONE device->host transfer: token/active/bad
            # planes arrive stacked [3, K, B] (on-device sampling + stop
            # masks mean nothing else ever needs to cross per step)
            trace_t, trace_a, trace_b = self._readback(trace)
            trace_a = trace_a.astype(bool)
            trace_b = trace_b.astype(bool)
        self.host_syncs += 1
        if self._cache.quantized:
            _M_KV_DEQUANT.inc(k * self.cfg.n_layers)
        serve._M_HOST_SYNCS.inc()
        stepped = int(active.sum())
        # first poisoned step per slot: tokens before it are sound, the
        # slot quarantines at it, and the trace replay below simply never
        # reads the poisoned row — survivors stay bit-equal by row
        # independence (serve._first_bad_steps)
        first_bad = serve._first_bad_steps(trace_a, trace_b)
        committed = 0
        for j in range(trace_t.shape[0]):
            for slot, st in enumerate(self._slots):
                if st is None or not trace_a[j][slot]:
                    continue
                if j >= first_bad.get(slot, k):
                    continue
                st.tokens.append(int(trace_t[j][slot]))
                self.telemetry.on_commit(st.request_id)
                committed += 1
                self._retire(slot)
        self.telemetry.burst_end(stepped)
        for slot in sorted(first_bad):
            if self._slots[slot] is not None:
                serve._quarantine_slot(
                    self, slot, "nan_logits",
                    f"non-finite logits at burst step {first_bad[slot]}",
                )
        serve._M_TOKENS.inc(committed)
        self._update_gauges()
        self._last_step_s = time.perf_counter() - t0
        serve._M_STEP_LATENCY.observe(self._last_step_s)
        return stepped

    def run_until_drained(self, max_steps: int = 10_000) -> None:
        from k8s_dra_driver_tpu.models import serve

        for _ in range(max_steps):
            admitting = bool(self._admitting)  # a chunk advancing IS progress
            if self.step_burst() == 0 and not admitting:
                if self.free_slots() == self.n_slots and not self._preempted:
                    return
                # every resident slot stalled, nothing preemptable, and
                # nothing can retire to free a block: the pool is too
                # small for this resident set
                raise serve._wedge_error(
                    self, "engine wedged: resident slots, no progress"
                )
        raise serve._wedge_error(self, "serving loop did not drain")

    def pump(
        self, requests, max_steps: int = 100_000,
        queue_limit: int | None = None,
    ) -> list:
        """Continuous-batching drive over the pool: admit ``requests`` as
        slots AND blocks free, burst-stepping in between; returns the
        completions.  Composes with chunked admission, prefix sharing,
        speculative rounds, LoRA and preemption (see serve._pump).
        ``queue_limit`` bounds the host-side admission queue: overflow is
        SHED newest-first as a typed Completion (status="shed") carrying
        a retry-after — no device work is dispatched for a shed request."""
        from k8s_dra_driver_tpu.models import serve

        return serve._pump(self, requests, max_steps, queue_limit)

    def completions(self) -> list:
        out, self._completions = self._completions, []
        return out

    def stats(self):
        """The EngineStats load/latency snapshot (models/telemetry.py) —
        the per-replica routing signal: queue depth, resident/free slots,
        free pool blocks, rolling TTFT/TPOT quantiles, shed/quarantine
        tallies."""
        return self.telemetry.stats()

    def cancel(self, request_id: int) -> bool:
        """Cancel an in-flight request: resident slots retire immediately
        (blocks refund, typed "cancelled" completion with the tokens so
        far); a mid-admission request also drops its prefill-queue entry;
        a PREEMPTED (parked) request just unparks — it holds no blocks.
        Host-side between steps, like the dense engine.  Returns whether
        the id was found."""
        from k8s_dra_driver_tpu.models import serve

        for slot, st in enumerate(self._slots):
            if st is not None and st.request_id == request_id:
                self._admitting = [
                    a for a in self._admitting if a["slot"] != slot
                ]
                serve._early_retire(self, slot, "cancelled", "cancelled by caller")
                return True
        for i, r in enumerate(self._preempted):
            st = r["st"]
            if st.request_id == request_id:
                self._preempted.pop(i)
                serve._retire_parked(self, st, "cancelled", "cancelled by caller")
                return True
        return False

    def _capture_kv(self, slot: int, valid_len: int):
        """Host copy of this slot's live KV in the CANONICAL payload
        layout [L, valid_len, Hkv, hd]: gather the owned block stripes
        [L, nb, Hkv, hd, bs], move positions off the lane axis, flatten
        and clip.  Bit-identical to a dense capture of the same stream by
        the paged-prefill construction (dense prefill then block
        scatter).  One counted device sync, like the dense twin.

        QUANTIZED pools carry the RAW quantized values + per-block scales
        VERBATIM (dequantizing to floats would not round-trip: requantize
        of (127*s)/127 is not bit-stable in f32), over the PADDED
        ``nb * bs`` extent rather than clipped to valid_len — the restore
        scatter then reproduces the exact pool bytes, which is what makes
        same-seed continuation after restore/handoff bit-exact.  int4
        payloads repack the lane-axis nibbles onto the head_dim axis so
        the wire form is seq-major like every other payload; the repack
        is pure integer ops (exact)."""
        from k8s_dra_driver_tpu.models import serve

        bs = self.block_size
        nb = blocks_needed(valid_len, bs)
        ids = np.asarray(self._owned[slot][:nb], np.int32)
        kb = self._readback(self._cache.k[:, jnp.asarray(ids)])
        vb = self._readback(self._cache.v[:, jnp.asarray(ids)])
        cfg = self.cfg
        l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        if self._cache.quantized:
            kv_dtype = self._cache.kv_dtype
            ksc = self._readback(self._cache.k_scale[:, jnp.asarray(ids)])
            vsc = self._readback(self._cache.v_scale[:, jnp.asarray(ids)])
            self.host_syncs += 1
            serve._M_HOST_SYNCS.inc()
            if kv_dtype == "int4":
                kb = np.asarray(quant.unpack_int4(kb, axis=-1))
                vb = np.asarray(quant.unpack_int4(vb, axis=-1))
            k = np.transpose(kb, (0, 1, 4, 2, 3)).reshape(l, nb * bs, hkv, hd)
            v = np.transpose(vb, (0, 1, 4, 2, 3)).reshape(l, nb * bs, hkv, hd)
            if kv_dtype == "int4":
                k = np.asarray(quant.pack_int4(k, axis=-1))
                v = np.asarray(quant.pack_int4(v, axis=-1))
            return serve.KVSlice(
                k=np.ascontiguousarray(k), v=np.ascontiguousarray(v),
                valid_len=valid_len, n_layers=l, kv_heads=hkv, head_dim=hd,
                dtype=kv_dtype,
                k_scale=np.ascontiguousarray(ksc),
                v_scale=np.ascontiguousarray(vsc),
                block_size=bs,
            )
        self.host_syncs += 1
        serve._M_HOST_SYNCS.inc()
        k = np.transpose(kb, (0, 1, 4, 2, 3)).reshape(l, nb * bs, hkv, hd)
        v = np.transpose(vb, (0, 1, 4, 2, 3)).reshape(l, nb * bs, hkv, hd)
        k = np.ascontiguousarray(k[:, :valid_len])
        v = np.ascontiguousarray(v[:, :valid_len])
        return serve.KVSlice(
            k=k, v=v, valid_len=valid_len, n_layers=l, kv_heads=hkv,
            head_dim=hd, dtype=str(k.dtype),
        )

    def _handoff_retire(self, slot: int, temp, key, adapter: int) -> None:
        """First-token retire for the disaggregated prefill pool: capture
        the entry + KV payload (prefill-written prompt positions), refund
        the slot's blocks, and queue it for :meth:`take_handoffs` — no
        Completion here, the decode-pool restorer delivers it.  The
        refcounted free keeps prefix-shared blocks pooled (the payload
        already copied their bytes out)."""
        from k8s_dra_driver_tpu.models import serve

        st = self._slots[slot]
        entry = serve._snapshot_request(
            st, float(temp), np.asarray(key), adapter, self._prio[slot],
            trace=self.telemetry.export_trace(st.request_id),
        )
        entry["kv"] = self._capture_kv(slot, st.prompt_len)
        self._slots[slot] = None
        self._alloc_for(slot).free(self._owned[slot])
        self._owned[slot] = []
        self._table_np[slot, :] = NULL_BLOCK
        self._upload_table()
        self.telemetry.drop_trace(st.request_id)
        self._handoffs.append(entry)
        JOURNAL.record(
            "serve", "request.handoff", correlation=f"req-{st.request_id}",
            slot=slot, kv_bytes=entry["kv"].nbytes,
        )
        self._update_gauges()

    def take_handoffs(self) -> list[dict]:
        """Drain the handoff queue: snapshot entries (with KV payloads)
        for requests that retired at first token under
        ``submit(handoff=True)``."""
        out, self._handoffs = self._handoffs, []
        return out

    def _restore_inject(self, req: dict, st, kv) -> bool:
        """Direct KV inject for a snapshot entry carrying a compatible
        payload: claim a slot + ALL-EXCLUSIVE blocks (storable=0 — a
        shared prefix-store block must never be scatter-written), scatter
        the payload block stripes, and install the slot exactly as
        _readmit would after its re-prefill.  Returns False when no
        capacity or the scatter cannot be used — the caller falls back to
        the parked re-prefill path."""
        from k8s_dra_driver_tpu.models import serve

        tokens = st.tokens
        bs = self.block_size
        adapter = int(req.get("adapter", 0))
        need = blocks_needed(len(tokens) + 1, bs)
        picked = self._pick_slot(tokens, need, 0, adapter)
        if picked is None:
            return False
        slot, ids, _cached = picked
        try:
            cfg = self.cfg
            l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
            nb = blocks_needed(kv.valid_len, bs)
            pad = nb * bs
            ids_j = jnp.asarray(np.asarray(ids[:nb], np.int32))
            if self._cache.quantized:
                # inverse of the quantized capture: payloads already carry
                # the padded extent of RAW values, so the scatter below
                # reproduces the origin pool bytes exactly (the geometry
                # gate guaranteed matching kv_dtype and block_size)
                k_p, v_p = kv.k, kv.v
                if kv.dtype == "int4":
                    k_p = np.asarray(quant.unpack_int4(k_p, axis=-1))
                    v_p = np.asarray(quant.unpack_int4(v_p, axis=-1))
                kb = np.transpose(k_p.reshape(l, nb, bs, hkv, hd), (0, 1, 3, 4, 2))
                vb = np.transpose(v_p.reshape(l, nb, bs, hkv, hd), (0, 1, 3, 4, 2))
                if kv.dtype == "int4":
                    kb = np.asarray(quant.pack_int4(kb, axis=-1))
                    vb = np.asarray(quant.pack_int4(vb, axis=-1))
                self._cache = PagedKVCache(
                    k=self._cache.k.at[:, ids_j].set(
                        jnp.asarray(kb, self._cache.k.dtype)
                    ),
                    v=self._cache.v.at[:, ids_j].set(
                        jnp.asarray(vb, self._cache.v.dtype)
                    ),
                    k_scale=self._cache.k_scale.at[:, ids_j].set(
                        jnp.asarray(kv.k_scale, jnp.float32)
                    ),
                    v_scale=self._cache.v_scale.at[:, ids_j].set(
                        jnp.asarray(kv.v_scale, jnp.float32)
                    ),
                )
            else:
                k_p = np.zeros((l, pad, hkv, hd), kv.k.dtype)
                v_p = np.zeros((l, pad, hkv, hd), kv.v.dtype)
                k_p[:, : kv.valid_len] = kv.k
                v_p[:, : kv.valid_len] = kv.v
                # inverse of the capture gather: [L, nb*bs, Hkv, hd] ->
                # block stripes [L, nb, Hkv, hd, bs] (positions back onto
                # the lane axis)
                kb = np.transpose(k_p.reshape(l, nb, bs, hkv, hd), (0, 1, 3, 4, 2))
                vb = np.transpose(v_p.reshape(l, nb, bs, hkv, hd), (0, 1, 3, 4, 2))
                self._cache = PagedKVCache(
                    k=self._cache.k.at[:, ids_j].set(
                        jnp.asarray(kb, self._cache.k.dtype)
                    ),
                    v=self._cache.v.at[:, ids_j].set(
                        jnp.asarray(vb, self._cache.v.dtype)
                    ),
                )
            self._owned[slot] = ids
            self._table_np[slot, :] = NULL_BLOCK
            self._table_np[slot, :need] = ids
            self._upload_table()
            self._adapter_ids = self._adapter_ids.at[slot].set(adapter)
            self._prio[slot] = int(req.get("priority", 0))
            if self.spec_gamma > 0:
                # the draft cache never rides a handoff — its layers
                # re-prefill (any draft state verifies to the same greedy
                # target stream)
                padded = np.zeros((1, self.prompt_bucket), np.int32)
                padded[0, : len(tokens)] = tokens
                self._run_draft_prefill(padded, len(tokens), slot)
        except BaseException:
            # a failed inject (device OOM mid-scatter, draft prefill death)
            # must refund — the slot never became resident, so no retire
            # path will ever free these blocks
            self._alloc_for(slot).free(ids)
            self._owned[slot] = []
            self._table_np[slot, :] = NULL_BLOCK
            raise
        self._slots[slot] = st
        self._last = self._last.at[slot].set(tokens[-1])
        self._pos = self._pos.at[slot].set(len(tokens) - 1)
        self._temps = self._temps.at[slot].set(float(req["temperature"]))
        self._keys = self._keys.at[slot].set(
            jnp.asarray(np.asarray(req["key"], dtype=np.uint32))
        )
        self._stop_pos = self._stop_pos.at[slot].set(
            st.prompt_len + serve._slot_budget(st) - 1
        )
        self._retire(slot)  # history may already sit at its budget
        self._update_gauges()
        return True

    # -- fleet prefix-cache tier surface (models/fleet_prefix.py) ----------

    def prefix_geometry(self) -> dict:
        """KVSlice geometry a fleet-tier puller must match to inject here.
        ``kv_dtype`` is the pool *storage* label ("int8"/"int4" for
        quantized pools, else the float dtype string) — the bit-equality
        contract only holds when payload and pool bytes are the same
        representation."""
        label = self.kv_dtype or str(jnp.zeros((), self.cache_dtype).dtype)
        return {
            "block_size": self.block_size,
            "kv_dtype": label,
            "n_layers": self.cfg.n_layers,
            "kv_heads": self.cfg.kv_heads,
            "head_dim": self.cfg.head_dim,
        }

    def local_prefix_depth(self, prompt, adapter: int = 0) -> int:
        """Deepest contiguous cached-prefix run (in TOKENS) any shard's
        store already holds for this prompt.  Read-only: no LRU touch, no
        refs taken, no device work."""
        if self.prefix_cache_blocks <= 0:
            return 0
        prompt = [int(t) for t in prompt]
        bs = self.block_size
        limit = min((len(prompt) - 1) // bs, (self.prompt_bucket - 1) // bs)
        best = 0
        for store in self._prefix_stores:
            depth = 0
            for i in range(limit):
                if self._prefix_key(prompt, i, adapter) not in store:
                    break
                depth = i + 1
            best = max(best, depth)
        return best * bs

    def export_prefix_kv(self, prompt, max_tokens=None, adapter: int = 0):
        """Fleet-tier pull source: capture the deepest contiguous cached
        prefix run for ``prompt`` as a canonical KVSlice (valid_len =
        depth * block_size), or None when nothing is cached.  Same gather
        + readback construction as :meth:`_capture_kv` — which is what
        makes a remote-injected prefix bit-equal to computing it locally.
        One counted device sync when something is exported."""
        del max_tokens  # advisory in the wire request; depth caps it
        if self.prefix_cache_blocks <= 0:
            return None
        from k8s_dra_driver_tpu.models import serve

        prompt = [int(t) for t in prompt]
        bs = self.block_size
        limit = min((len(prompt) - 1) // bs, (self.prompt_bucket - 1) // bs)
        best_ids: list[int] = []
        for store in self._prefix_stores:
            ids: list[int] = []
            for i in range(limit):
                key = self._prefix_key(prompt, i, adapter)
                bid = store.get(key)
                if bid is None:
                    break
                ids.append(int(bid))
            if len(ids) > len(best_ids):
                best_ids = ids
        nb = len(best_ids)
        if nb == 0:
            return None
        valid_len = nb * bs
        cfg = self.cfg
        l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        ids_j = jnp.asarray(np.asarray(best_ids, np.int32))
        kb = self._readback(self._cache.k[:, ids_j])
        vb = self._readback(self._cache.v[:, ids_j])
        if self._cache.quantized:
            kv_dtype = self._cache.kv_dtype
            ksc = self._readback(self._cache.k_scale[:, ids_j])
            vsc = self._readback(self._cache.v_scale[:, ids_j])
            self.host_syncs += 1
            serve._M_HOST_SYNCS.inc()
            if kv_dtype == "int4":
                kb = np.asarray(quant.unpack_int4(kb, axis=-1))
                vb = np.asarray(quant.unpack_int4(vb, axis=-1))
            k = np.transpose(kb, (0, 1, 4, 2, 3)).reshape(l, valid_len, hkv, hd)
            v = np.transpose(vb, (0, 1, 4, 2, 3)).reshape(l, valid_len, hkv, hd)
            if kv_dtype == "int4":
                k = np.asarray(quant.pack_int4(k, axis=-1))
                v = np.asarray(quant.pack_int4(v, axis=-1))
            return serve.KVSlice(
                k=np.ascontiguousarray(k), v=np.ascontiguousarray(v),
                valid_len=valid_len, n_layers=l, kv_heads=hkv, head_dim=hd,
                dtype=kv_dtype,
                k_scale=np.ascontiguousarray(ksc),
                v_scale=np.ascontiguousarray(vsc),
                block_size=bs,
            )
        self.host_syncs += 1
        serve._M_HOST_SYNCS.inc()
        k = np.transpose(kb, (0, 1, 4, 2, 3)).reshape(l, valid_len, hkv, hd)
        v = np.transpose(vb, (0, 1, 4, 2, 3)).reshape(l, valid_len, hkv, hd)
        return serve.KVSlice(
            k=np.ascontiguousarray(k), v=np.ascontiguousarray(v),
            valid_len=valid_len, n_layers=l, kv_heads=hkv, head_dim=hd,
            dtype=str(k.dtype),
        )

    def inject_prefix_kv(self, prompt, kv, adapter: int = 0) -> int:
        """Fleet-tier pull sink: scatter a pulled prefix payload into
        fresh pool blocks and insert them into the prefix store, so the
        next ``submit()`` for this prompt takes the EXISTING prefix-hit
        admission path (``_pick_slot`` -> ``_run_prefill_suffix``) — the
        path whose bit-equality vs cold prefill is already pinned by the
        serve/disagg test matrices.  Returns tokens installed; 0 means the
        caller must cold-prefill (geometry mismatch, nothing new to add,
        or no free blocks — never an error).  Quantized pools require the
        exact kv_dtype AND block_size (scales are per-block); float
        payloads may re-block onto our granularity, installing the whole
        receiver-blocks they cover."""
        from k8s_dra_driver_tpu.models import serve

        if self.prefix_cache_blocks <= 0 or not isinstance(kv, serve.KVSlice):
            return 0
        cfg = self.cfg
        bs = self.block_size
        l, hkv, hd = cfg.n_layers, cfg.kv_heads, cfg.head_dim
        if (kv.n_layers, kv.kv_heads, kv.head_dim) != (l, hkv, hd):
            return 0
        label = self.kv_dtype or str(jnp.zeros((), self.cache_dtype).dtype)
        if self._cache.quantized:
            if (not kv.quantized or kv.dtype != label or kv.block_size != bs
                    or kv.valid_len % bs != 0 or kv.k_scale is None):
                return 0
        elif kv.quantized or kv.dtype != label:
            return 0
        prompt = [int(t) for t in prompt]
        limit = min((len(prompt) - 1) // bs, (self.prompt_bucket - 1) // bs)
        depth = min(kv.valid_len // bs, limit)
        if depth < 1:
            return 0
        # One target shard: the one with the most free blocks (prefix hits
        # are shard-local, so spreading a prefix across shards helps no
        # admission).
        g = max(range(len(self._allocs)),
                key=lambda i: self._allocs[i].free_blocks)
        store = self._prefix_stores[g]
        missing: list[int] = []
        for i in range(depth):
            key = self._prefix_key(prompt, i, adapter)
            if key in store:
                store.move_to_end(key)
            else:
                missing.append(i)
        if not missing:
            return 0
        try:
            ids = self._allocs[g].alloc(len(missing))
        except OutOfBlocks:
            return 0
        try:
            sel = np.asarray(missing, np.int64)
            ids_j = jnp.asarray(np.asarray(ids, np.int32))
            if self._cache.quantized:
                k_p, v_p = np.asarray(kv.k), np.asarray(kv.v)
                nb_total = kv.valid_len // bs
                if kv.dtype == "int4":
                    k_p = np.asarray(quant.unpack_int4(k_p, axis=-1))
                    v_p = np.asarray(quant.unpack_int4(v_p, axis=-1))
                kb = np.transpose(
                    k_p.reshape(l, nb_total, bs, hkv, hd), (0, 1, 3, 4, 2)
                )[:, sel]
                vb = np.transpose(
                    v_p.reshape(l, nb_total, bs, hkv, hd), (0, 1, 3, 4, 2)
                )[:, sel]
                if kv.dtype == "int4":
                    kb = np.asarray(quant.pack_int4(kb, axis=-1))
                    vb = np.asarray(quant.pack_int4(vb, axis=-1))
                self._cache = PagedKVCache(
                    k=self._cache.k.at[:, ids_j].set(
                        jnp.asarray(kb, self._cache.k.dtype)
                    ),
                    v=self._cache.v.at[:, ids_j].set(
                        jnp.asarray(vb, self._cache.v.dtype)
                    ),
                    k_scale=self._cache.k_scale.at[:, ids_j].set(
                        jnp.asarray(np.asarray(kv.k_scale)[:, sel], jnp.float32)
                    ),
                    v_scale=self._cache.v_scale.at[:, ids_j].set(
                        jnp.asarray(np.asarray(kv.v_scale)[:, sel], jnp.float32)
                    ),
                )
            else:
                k_p = np.asarray(kv.k)[:, : depth * bs]
                v_p = np.asarray(kv.v)[:, : depth * bs]
                kb = np.transpose(
                    k_p.reshape(l, depth, bs, hkv, hd), (0, 1, 3, 4, 2)
                )[:, sel]
                vb = np.transpose(
                    v_p.reshape(l, depth, bs, hkv, hd), (0, 1, 3, 4, 2)
                )[:, sel]
                self._cache = PagedKVCache(
                    k=self._cache.k.at[:, ids_j].set(
                        jnp.asarray(kb, self._cache.k.dtype)
                    ),
                    v=self._cache.v.at[:, ids_j].set(
                        jnp.asarray(vb, self._cache.v.dtype)
                    ),
                )
            for i, bid in zip(missing, ids):
                key = self._prefix_key(prompt, i, adapter)
                store[key] = int(bid)
                if self.on_prefix_store is not None:
                    n = (i + 1) * bs
                    self.on_prefix_store(tuple(prompt[:n]), n, adapter)
        except BaseException:
            # a failed scatter must refund: no store entry owns these yet,
            # so no retire/evict path would ever free them (the
            # partial-pull-unpinned chaos invariant)
            self._allocs[g].free(ids)
            raise
        self._trim_prefix_store(store, g)
        self._update_gauges()
        return len(missing) * bs

    def snapshot_active(self, include_kv: bool = False) -> dict:
        """Graceful drain over the pool: capture every in-flight request —
        resident slots, slots still mid-chunked-admission (their history
        is just the prompt), and preempted/parked requests — as the same
        JSON shape the dense engine emits (serve._snapshot_request), so a
        snapshot restores into EITHER engine class.  Host-only: one
        readback of the sampler vectors, zero decode dispatches, zero
        block traffic.

        ``include_kv=True`` attaches resident (activated) slots' live
        cache blocks as canonical-layout payloads under ``"kv"``
        (serve.KVSlice); mid-admission and parked entries carry none —
        they re-prefill at restore like today.  KV-bearing snapshots are
        NOT JSON (the default keeps the wedge-bundle json.dumps path
        intact)."""
        from k8s_dra_driver_tpu.models import serve

        temps = self._readback(self._temps)
        keys = self._readback(self._keys)
        ads = self._readback(self._adapter_ids)
        admitting = {a["slot"]: a for a in self._admitting}
        reqs = []
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            if slot in admitting:
                # device sampler vectors are not set until activation —
                # the queue entry is the source of truth mid-admission
                adm = admitting[slot]
                reqs.append(serve._snapshot_request(
                    st, float(adm["temp"]), adm["key"],
                    int(adm.get("adapter", 0)), self._prio[slot],
                    trace=self.telemetry.export_trace(st.request_id),
                ))
            else:
                req = serve._snapshot_request(
                    st, float(temps[slot]), keys[slot], int(ads[slot]),
                    self._prio[slot],
                    trace=self.telemetry.export_trace(st.request_id),
                )
                if include_kv and len(st.tokens) > 1:
                    req["kv"] = self._capture_kv(slot, len(st.tokens) - 1)
                reqs.append(req)
        for r in self._preempted:
            reqs.append(serve._snapshot_request(
                r["st"], float(r["temp"]), r["key"],
                int(r.get("adapter", 0)), int(r.get("priority", 0)),
                trace=self.telemetry.export_trace(r["st"].request_id),
            ))
        return {
            "engine": type(self).__name__,
            "next_id": self._next_id,
            "requests": reqs,
        }

    def restore(self, snapshot: dict, merge: bool = False) -> list[int]:
        """Rebuild a drained batch in THIS (fresh, idle) engine with
        bit-equal continuation.  Every snapshot entry parks on the
        re-admission queue and drains through :meth:`_readmit` — the SAME
        re-prefill path preemption resume uses, already proven bit-exact
        (tokens-so-far re-prefill as the prompt; the next step samples at
        the original position with the original fold-by-position key).
        Requests the pool cannot hold yet simply STAY parked and admit as
        capacity frees — restore into a smaller pool degrades gracefully
        instead of failing.  Histories grown past ``prompt_bucket`` cannot
        re-prefill in one pass and are delivered as errored Completions
        (the preemption resumability boundary).  Returns the request ids
        accepted for restoration (parked or resident).

        ``merge=True`` restores INTO a live engine (the fleet router's
        evacuation target): entries join the re-admission queue behind
        whatever is already parked and drain as the pool frees, while
        resident streams keep decoding untouched — readmission is the
        preemption-resume path, already proven bit-exact on a busy
        pool."""
        from k8s_dra_driver_tpu.models import serve
        from k8s_dra_driver_tpu.models.serve import _Slot

        serve.check_restorable(snapshot)
        if not merge and (
            (self.n_slots - self.free_slots()) or self._admitting or self._preempted
        ):
            raise RuntimeError("restore() needs an idle engine")
        restored: list[int] = []
        for req in sorted(snapshot["requests"], key=lambda r: r["request_id"]):
            # rebuild the request's timeline FIRST: even an unrestorable
            # entry retires against its original submit/first-token stamps
            self.telemetry.import_trace(
                int(req["request_id"]), req.get("trace")
            )
            tokens = [int(t) for t in req["tokens"]]
            if len(tokens) > self.prompt_bucket:
                serve._unrestorable(
                    self, req,
                    f"history {len(tokens)} exceeds prompt_bucket "
                    f"{self.prompt_bucket}",
                )
                continue
            st = _Slot(
                int(req["request_id"]), tokens, int(req["prompt_len"]),
                int(req["max_tokens"]), req.get("deadline"),
            )
            kv = req.get("kv")
            if kv is not None and serve._kv_geometry_ok(self, kv, len(tokens)):
                if self._restore_inject(req, st, kv):
                    restored.append(st.request_id)
                    JOURNAL.record(
                        "serve", "request.restore",
                        correlation=f"req-{st.request_id}",
                        resumed_at=len(tokens), kv_inject=True,
                    )
                    self.telemetry.on_restore(
                        st.request_id, resumed_at=len(tokens)
                    )
                    continue
                # no slot/blocks right now: park WITHOUT the payload — by
                # the time capacity frees the blocks could be long gone,
                # so the proven re-prefill path takes over
                serve._M_DISAGG_FALLBACK.inc(reason="no_capacity")
            elif kv is not None:
                serve._M_DISAGG_FALLBACK.inc(reason="incompatible")
            self._preempted.append(
                dict(
                    st=st, temp=float(req["temperature"]),
                    key=np.asarray(req["key"], dtype=np.uint32),
                    adapter=int(req.get("adapter", 0)),
                    priority=int(req.get("priority", 0)),
                )
            )
            restored.append(st.request_id)
            JOURNAL.record(
                "serve", "request.restore",
                correlation=f"req-{st.request_id}", resumed_at=len(tokens),
            )
            self.telemetry.on_restore(st.request_id, resumed_at=len(tokens))
        self._preempted.sort(key=lambda r: -r.get("priority", 0))
        self._next_id = max(
            self._next_id,
            int(snapshot.get("next_id", 0)),
            max((int(r["request_id"]) for r in snapshot["requests"]),
                default=-1) + 1,
        )
        self._readmit()  # admit what fits now; the rest drains via step()
        self._update_gauges()
        return restored

    def release_active(self) -> int:
        """Migration tail: free every resident slot, refund its pool
        blocks, and drop parked/mid-admission entries WITHOUT delivering
        completions — the streams were just captured by
        ``snapshot_active()`` and now live in another engine, so retiring
        them here would double-deliver every request (and the dead
        replica's block accounting must still balance for leak audits).
        Returns the number of requests released."""
        released = 0
        self._admitting = []
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            self._slots[slot] = None
            self._alloc_for(slot).free(self._owned[slot])
            self._owned[slot] = []
            self._table_np[slot, :] = 0  # NULL_BLOCK scratch sink
            self.telemetry.drop_trace(st.request_id)
            JOURNAL.record(
                "serve", "request.released",
                correlation=f"req-{st.request_id}", slot=slot,
                generated=len(st.tokens) - st.prompt_len,
            )
            released += 1
        self._upload_table()
        for r in self._preempted:  # parked entries hold no blocks
            st = r["st"]
            self.telemetry.drop_trace(st.request_id)
            JOURNAL.record(
                "serve", "request.released",
                correlation=f"req-{st.request_id}", slot=-1,
                generated=len(st.tokens) - st.prompt_len,
            )
            released += 1
        self._preempted = []
        self._update_gauges()
        return released

    # -- internals ---------------------------------------------------------
    def _burst_fn(self, k: int):
        """Compiled K-step fused burst, cached per distinct K.  Only two
        lengths ever occur — the configured ``sync_interval`` and the
        tight-pool K=1 fallback — so at most two traces live here."""
        fn = self._pipe_fns.get(k)
        if fn is not None:
            return fn
        if self.mesh is None:
            from k8s_dra_driver_tpu.models import serve

            fn = serve.shared_jit(
                _paged_pipelined_burst, donate_argnums=(1,),
                **self._pipe_kw, k=k,
            )
        else:
            from jax.sharding import PartitionSpec as P

            ax = self.slot_axis
            cache_p = PagedKVCache(k=P(None, ax), v=P(None, ax))
            row_p = P(ax)
            # [3, K, n_slots]: slots shard, planes and steps don't
            trace_p = P(None, None, ax)
            ad_p = (P(), P(ax)) if self.adapter_bank is not None else P()
            fn = jax.jit(
                jax.shard_map(
                    functools.partial(
                        _paged_pipelined_burst, **self._pipe_kw, k=k
                    ),
                    mesh=self.mesh,
                    in_specs=(P(), cache_p, row_p, row_p, row_p, row_p,
                              row_p, row_p, row_p, ad_p, row_p),
                    out_specs=(trace_p, cache_p, row_p, row_p, row_p),
                ),
                donate_argnums=(1,),
            )
        self._pipe_fns[k] = fn
        return fn

    def _group(self, slot: int) -> int:
        """Pool shard owning this slot (always 0 when unsharded) — the
        contiguous split NamedSharding applies to the slot axis."""
        return slot // self._spg

    def _alloc_for(self, slot: int) -> BlockAllocator:
        return self._allocs[self._group(slot)]

    def _pick_slot(self, tokens, need: int, storable: int, adapter: int):
        """Admission slot choice, shared by submit() and _readmit(): walk
        the free slots for the first whose SHARD can serve ``need`` blocks
        (prefix hits are shard-local — a stored block only helps requests
        admitted to the shard holding it — and count toward ``need``).
        One candidate per shard: a second slot on a shard that just
        refused cannot do better.  Deterministic order, so every
        controller of a multi-process mesh picks the same slot.  On
        success the blocks are ALLOCATED: returns (slot, ids, n_cached);
        None when no free slot / no shard has capacity (any prefix refs
        taken along the way are dropped again)."""
        tried: set[int] = set()
        for cand in range(self.n_slots):
            if self._slots[cand] is not None:
                continue
            g = self._group(cand)
            if g in tried:
                continue
            tried.add(g)
            hits: list[int] = []
            if self.prefix_cache_blocks > 0:
                store = self._prefix_stores[g]
                for i in range(storable):
                    key = self._prefix_key(tokens, i, adapter)
                    if key not in store:
                        break
                    store.move_to_end(key)  # LRU touch
                    hits.append(self._allocs[g].share(store[key]))
            try:
                ids = hits + self._allocs[g].alloc(need - len(hits))
            except OutOfBlocks:
                self._allocs[g].free(hits)  # drop the hit refs we just took
                continue
            return cand, ids, len(hits)
        return None

    def _upload_table(self) -> None:
        """Host block table -> device, sharded over the slot axis when a
        mesh is set.  device_put FROM NUMPY on purpose: host arrays commit
        to a global sharding from every process of a multi-controller
        mesh; re-sharding a committed local device array would not."""
        if self.mesh is None:
            self._table = jnp.asarray(self._table_np)
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            self._table = jax.device_put(
                self._table_np, NamedSharding(self.mesh, P(self.slot_axis))
            )

    def _slot_device(self, arr):
        """Host per-slot vector -> device, slot-axis sharded under a mesh
        (same numpy-origin rule as :meth:`_upload_table`)."""
        if self.mesh is None:
            return jnp.asarray(arr)
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return jax.device_put(
            np.asarray(arr), NamedSharding(self.mesh, P(self.slot_axis))
        )

    def _slot_onehot(self, slot: int):
        """The sharded stand-in for a global slot index: shard_map bodies
        can't interpret one (rows are shard-local), a one-hot they can."""
        return self._slot_device(np.arange(self.n_slots) == slot)

    def _group_flag(self, group: int):
        """[axis_size] one-hot over pool shards — each device of the mesh
        sees a single bool: 'do I keep this admission's block writes'."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        flag = np.zeros((self._axis_size,), bool)
        flag[group] = True
        return jax.device_put(
            flag, NamedSharding(self.mesh, P(self.slot_axis))
        )

    def _readback(self, x) -> np.ndarray:
        """Device -> host for state that may be sharded across PROCESSES:
        remote shards cannot be addressed directly, so the multi-process
        path allgathers (every controller runs the same step, so every
        controller needs the same full vector anyway)."""
        if self._multiprocess:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(x)

    def _run_prefill(self, padded, prefill_row, slot, row_ad) -> None:
        """Whole-prompt admission prefill into ``slot``'s shard."""
        if self.mesh is None:
            self._cache, _ = self._prefill_fn(
                self.params, padded, self._cache, prefill_row, row_ad
            )
        else:
            self._cache, _ = self._prefill_fn(
                self.params, padded, self._cache, prefill_row,
                self._group_flag(self._group(slot)), row_ad,
            )

    def _run_prefill_chunk(
        self, padded, prefill_row, done, chunk_len, slot, row_ad
    ) -> None:
        """Chunked/suffix admission prefill.  Mesh path compiles one
        masked variant per distinct ``chunk_len`` (same bounded set as the
        unsharded module fns — the chunk width and the final widths)."""
        if self.mesh is None:
            self._cache = paged_prefill_chunk(
                self.params, padded, self._cache, prefill_row, done,
                cfg=self.cfg, chunk_len=chunk_len, adapters=row_ad,
            )
            return
        fn = self._chunk_fns.get(chunk_len)
        if fn is None:
            from jax.sharding import PartitionSpec as P

            ax = self.slot_axis
            cache_p = PagedKVCache(k=P(None, ax), v=P(None, ax))
            fn = jax.jit(
                jax.shard_map(
                    functools.partial(
                        _paged_prefill_chunk_masked, cfg=self.cfg,
                        chunk_len=chunk_len,
                    ),
                    mesh=self.mesh,
                    in_specs=(P(), P(), cache_p, P(), P(), P(ax), P()),
                    out_specs=cache_p,
                )
            )
            self._chunk_fns[chunk_len] = fn
        self._cache = fn(
            self.params, padded, self._cache, prefill_row, done,
            self._group_flag(self._group(slot)), row_ad,
        )

    def _run_prefill_suffix(self, padded, prefill_row, cached, slot, row_ad):
        """Prefix-hit admission = one chunk covering everything after the
        shared prefix (the engine-level twin of paged_prefill_suffix)."""
        self._run_prefill_chunk(
            padded, prefill_row, cached,
            padded.shape[1] - cached * self.block_size, slot, row_ad,
        )

    def _run_draft_prefill(self, padded, plen, slot) -> None:
        if self.mesh is None:
            self._d_cache = self._draft_prefill_fn(
                self.draft_params, self._d_cache, padded, plen, slot
            )
        else:
            self._d_cache = self._draft_prefill_fn(
                self.draft_params, self._d_cache, padded, plen,
                self._slot_onehot(slot),
            )

    def _first_token(self, padded, plen, slot, temp, key):
        """Admission tail dispatch: global slot index unsharded, one-hot
        sharded.  Returns the first generated token (replicated scalar)."""
        sel = slot if self.mesh is None else self._slot_onehot(slot)
        tok, self._cache = self._first_fn(
            self.params, self._cache, self._table, padded, plen, sel,
            jnp.float32(temp), np.asarray(key), self._adapters(),
        )
        return tok

    def _prefix_key(self, prompt: list[int], i: int, adapter: int):
        """Store key for prompt block i: token content, plus the adapter id
        when a bank is live — adapted k/v must never cross fine-tunes."""
        key = tuple(prompt[: (i + 1) * self.block_size])
        return (adapter, key) if self.adapter_bank is not None else key

    def _adapters(self):
        """(bank, per-slot ids) for the jitted fns, or None when off."""
        if self.adapter_bank is None:
            return None
        return (self.adapter_bank, self._adapter_ids)

    def _row_adapters(self, adapter: int):
        """Single-row adapter context for the [1, bucket] admission paths."""
        if self.adapter_bank is None:
            return None
        return (self.adapter_bank, jnp.asarray([adapter], jnp.int32))

    def _store_prefix_blocks(
        self, prompt: list[int], slot: int, storable: int, cached: int,
        adapter: int = 0,
    ) -> None:
        """Insert this admission's freshly computed full prompt blocks into
        the LRU prefix store (each entry holds one reference, so stored
        blocks outlive the request that computed them)."""
        if self.prefix_cache_blocks <= 0:
            return
        self.prefix_misses += max(storable - cached, 0)
        g = self._group(slot)
        store = self._prefix_stores[g]
        for i in range(cached, storable):
            key = self._prefix_key(prompt, i, adapter)
            if key in store:
                store.move_to_end(key)
                continue
            store[key] = self._allocs[g].share(int(self._table_np[slot, i]))
            if self.on_prefix_store is not None:
                n = (i + 1) * self.block_size
                self.on_prefix_store(tuple(prompt[:n]), n, adapter)
        self._trim_prefix_store(store, g)

    def _trim_prefix_store(self, store, g: int) -> None:
        while len(store) > self.prefix_cache_blocks:
            old_key, old = store.popitem(last=False)  # LRU evict
            self._allocs[g].free([old])
            if self.on_prefix_evict is not None:
                ad, toks = self._split_prefix_key(old_key)
                self.on_prefix_evict(toks, ad)

    def _split_prefix_key(self, key):
        """Inverse of :meth:`_prefix_key`: -> (adapter, token tuple)."""
        if self.adapter_bank is not None:
            return int(key[0]), key[1]
        return 0, key

    def _retire(self, slot: int) -> None:
        from k8s_dra_driver_tpu.models import serve

        done = serve.completion_if_done(
            self._slots[slot], self.eos_id, self.cfg.max_seq
        )
        if done is not None:
            self._completions.append(done)
            self._slots[slot] = None
            self._alloc_for(slot).free(self._owned[slot])
            self._owned[slot] = []
            self._table_np[slot, :] = NULL_BLOCK
            self._upload_table()
            self.telemetry.on_retire(
                done.request_id, done.status, len(done.generated)
            )

    def _update_gauges(self) -> None:
        from k8s_dra_driver_tpu.models import serve

        serve._M_OCCUPANCY.set(self.n_slots - self.free_slots())
        _M_POOL_FREE.set(self.free_blocks)
        kv_dtype = self.kv_dtype or str(jnp.zeros((), self.cache_dtype).dtype)
        _M_KV_BYTES.set(
            self.n_blocks * kv_block_bytes(
                self.cfg, self.block_size, self.kv_dtype or self.cache_dtype
            ),
            dtype=kv_dtype,
        )


@functools.partial(
    jax.jit,
    static_argnames=(
        "steps", "cfg", "block_size", "n_blocks", "cache_dtype",
        "attn_impl", "interpret", "chain", "kv_dtype",
    ),
)
def _paged_greedy_jit(
    params, prompt, table, *, steps, cfg, block_size, n_blocks,
    cache_dtype, attn_impl, interpret, chain, kv_dtype=None,
):
    """Whole paged greedy pass (cache init + prefill scatter + decode scan)
    as ONE compiled program — on tunneled devices the eager prefill's
    per-op dispatches would otherwise dominate.  ``chain > 1`` re-seeds the
    next pass from the tail of the previous one (the bench's RTT
    amortization discipline); the same table is re-prefilled in place."""
    b, p_len = prompt.shape
    total = p_len + steps
    step = functools.partial(
        paged_decode_step, cfg=cfg, attn_impl=attn_impl, interpret=interpret
    )

    def body(carry, pos):
        cache, tokens = carry
        token_in = jax.lax.dynamic_slice_in_dim(tokens, pos, 1, axis=1)[:, 0]
        logits, cache = step(params, cache, table, token_in, pos)
        nxt = jnp.argmax(logits, axis=-1).astype(tokens.dtype)
        tokens = jax.lax.dynamic_update_slice_in_dim(
            tokens, nxt[:, None], pos + 1, axis=1
        )
        return (cache, tokens), None

    out = prompt
    for _ in range(chain):
        cache = init_paged_cache(
            cfg, n_blocks, block_size, dtype=cache_dtype, kv_dtype=kv_dtype
        )
        cache, last_logits = paged_prefill(params, out, cache, table, cfg=cfg)
        first = jnp.argmax(last_logits, axis=-1).astype(prompt.dtype)
        tokens = jnp.concatenate(
            [out, jnp.zeros((b, steps), prompt.dtype)], axis=1
        )
        tokens = tokens.at[:, p_len].set(first)
        if steps > 1:
            positions = jnp.arange(p_len, total - 1, dtype=jnp.int32)
            (cache, tokens), _ = jax.lax.scan(body, (cache, tokens), positions)
        full = tokens
        out = jax.lax.dynamic_slice_in_dim(full, total - p_len, p_len, axis=1)
    return full


def paged_greedy_decode(
    params,
    prompt: jax.Array,
    steps: int,
    cfg: ModelConfig,
    *,
    block_size: int,
    n_blocks: int | None = None,
    cache_dtype=jnp.float32,
    attn_impl: str = "xla",
    interpret: bool = False,
    chain: int = 1,
    kv_dtype: str | None = None,
):
    """Greedy continuation over a paged cache: [B, P] -> [B, P+steps]
    (of the LAST chained pass; chain > 1 is the bench's RTT amortization).

    The correctness harness (and the bench's paged path): allocates each
    row's blocks up front (static table -> one compiled program), prefills,
    then scans :func:`paged_decode_step`.  Token-exact vs
    ``decode.greedy_decode(..., batch_prefill=True)`` -- tests pin it.
    ``kv_dtype`` "int8"/"int4" runs the quantized-pool mode (xla only).
    """
    if kv_dtype is not None and attn_impl != "xla":
        raise ValueError(f"kv_dtype={kv_dtype!r} needs attn_impl='xla'")
    b, p_len = prompt.shape
    total = p_len + steps
    mb = blocks_needed(total, block_size)
    if n_blocks is None:
        n_blocks = b * mb + 1  # + the null block
    alloc = BlockAllocator(n_blocks)
    table = np.zeros((b, mb), np.int32)
    for r in range(b):
        table[r] = alloc.alloc(mb)
    return _paged_greedy_jit(
        params, prompt, jnp.asarray(table), steps=steps, cfg=cfg,
        block_size=block_size, n_blocks=n_blocks,
        cache_dtype=jnp.dtype(cache_dtype), attn_impl=attn_impl,
        interpret=interpret, chain=chain, kv_dtype=kv_dtype,
    )
