"""Fleet observability plane: telemetry federation over the KV transport.

PRs 13 and 15 made the serving stack genuinely multi-process
(``transport.worker_main`` subprocess pools), but every observability
surface built before them — the flight recorder (utils/journal.py), the
tracer rings (utils/tracing.py), the metrics registry (utils/metrics.py),
every ``/debug/*`` endpoint — is process-local, so the control plane is
blind inside exactly the workers where decode actually runs.  This module
is the missing plane, in three layers:

**Federation.**  A ``TELEM`` frame (transport frame type 13) ships each
worker's journal tail, span records and metric-registry snapshot to the
control plane on a bounded cadence.  The frame body is
``u32 crc32 + json payload`` — CRC'd because telemetry rides the SAME
claimed socket as KV payloads and a corrupt frame must be dropped, never
crash the drain loop — and capped at ``TELEM_BUDGET_BYTES`` per frame so
telemetry can never starve KV bandwidth: an oversized snapshot sheds
stacks first, then oldest journal events, then oldest spans, then the
metrics text, and marks itself truncated.  ``FleetObservability`` (the
``FLEET`` singleton) merges ingested snapshots into instance-labeled
fleet views: ``/debug/fleet-journal``, ``/debug/fleet-traces``, and a
federated ``/metrics`` where each worker registry renders under its own
``instance=`` label.

**Distributed tracing.**  ``SpanRecord``s (utils/tracing.py) carry raw
``time.monotonic()`` timestamps from the recording process; the fleet
merger normalizes them into the control plane's clock domain using the
per-link offset the transport estimates from PING/PONG rtt
(``offset = pt - (t + rtt/2)``, the classic NTP half-rtt model), then
stitches every hop of one request — prefill, wire, decode, retire — into
a single span tree keyed by trace id.  Spans flushed before a worker
SIGKILL are preserved (they already federated), and the dead hop is
attributed with a synthetic ``hop.dead`` span from the hop context noted
at send time.

**SLO burn-rate monitor.**  ``SloBurnRateMonitor`` evaluates
miss-fraction burn rates over multiple simulated-time windows (5m/1h)
from per-request TTFT/TPOT scoring and from federated
``tpu_serve_ttft_seconds`` histogram deltas, emits
``tpu_slo_burn_rate{window=,tier=}`` gauges, journals alert transitions,
and exposes ``alerting`` as an input signal to ``FleetAutoscaler`` /
``PoolRebalancer``.

This module imports ONLY utils/ — no jax, no models — so the transport
layer can import it at module scope without cycles and control-plane
binaries stay accelerator-free.
"""

from __future__ import annotations

import json
import struct
import sys
import threading
import time
import traceback
import zlib
from collections import OrderedDict, deque

from ..utils.journal import JOURNAL
from ..utils.metrics import REGISTRY, escape_label_value
from ..utils.tracing import TRACES, SpanRecord

# ---------------------------------------------------------------------------
# TELEM frame codec.
#
# Budget ceiling: 48 KiB per frame.  A full snapshot (200 journal events,
# 256 spans, a ~10 KiB registry render) fits in ~35 KiB of JSON; the
# ceiling leaves headroom for stacks while staying two orders of magnitude
# under a single paged-KV layer shard, so a telemetry cadence tick can
# never displace meaningful KV bandwidth.  tools/perf_smoke.py
# ``check_obs_plane_overhead`` pins this ceiling.
# ---------------------------------------------------------------------------

TELEM_BUDGET_BYTES = 48 * 1024

_CRC = struct.Struct("!I")

_M_TELEM_FRAMES = REGISTRY.counter(
    "tpu_obs_telem_frames_total",
    "TELEM telemetry frames by outcome (shipped/ingested/crc_drop/decode_drop)",
)
_M_TELEM_BYTES = REGISTRY.counter(
    "tpu_obs_telem_bytes_total",
    "TELEM telemetry frame bytes by direction (tx=shipped, rx=ingested)",
)
_M_TELEM_TRUNCATED = REGISTRY.counter(
    "tpu_obs_telem_truncated_total",
    "TELEM snapshots that shed sections to fit the frame byte budget",
)
_M_INSTANCES = REGISTRY.gauge(
    "tpu_obs_instances",
    "Worker instances currently federated into the fleet observability plane",
)
_M_BURN = REGISTRY.gauge(
    "tpu_slo_burn_rate",
    "SLO error-budget burn rate per evaluation window and request tier",
)
_M_BURN_ALERT = REGISTRY.gauge(
    "tpu_slo_burn_alert",
    "1 while a tier's burn rate exceeds the alert threshold on every window",
)

# Closed outcome vocabulary for _M_TELEM_FRAMES — handlers must use these
# constants, never build label values from frame content (tools/lint.py
# polices the f-string/format forms).
SHIPPED = "shipped"
INGESTED = "ingested"
CRC_DROP = "crc_drop"
DECODE_DROP = "decode_drop"

_TX = "tx"
_RX = "rx"


def encode_telem(doc: dict) -> bytes:
    """TELEM frame body: ``u32 crc32(payload) + payload`` where payload is
    the UTF-8 JSON snapshot.  The transport's own frame header supplies
    the length prefix; the CRC here guards the PAYLOAD specifically so a
    fault-injected byte flip surfaces as a counted drop, not a JSON parse
    error deep in the control plane."""
    payload = json.dumps(doc, default=str).encode()
    return _CRC.pack(zlib.crc32(payload)) + payload


def decode_telem(body: bytes) -> dict | None:
    """Inverse of ``encode_telem``; returns None (and counts the drop) on
    CRC mismatch or malformed JSON — telemetry is lossy-by-design and a
    bad frame must never take down the drain loop it shares with KV."""
    if len(body) < _CRC.size:
        _M_TELEM_FRAMES.inc(outcome=DECODE_DROP)
        return None
    (crc,) = _CRC.unpack_from(body)
    payload = body[_CRC.size:]
    if zlib.crc32(payload) != crc:
        _M_TELEM_FRAMES.inc(outcome=CRC_DROP)
        return None
    try:
        doc = json.loads(payload.decode())
    except (UnicodeDecodeError, ValueError):
        _M_TELEM_FRAMES.inc(outcome=DECODE_DROP)
        return None
    if not isinstance(doc, dict):
        _M_TELEM_FRAMES.inc(outcome=DECODE_DROP)
        return None
    return doc


def _thread_stacks() -> dict[str, str]:
    names = {t.ident: t.name for t in threading.enumerate()}
    return {
        f"{names.get(tid, 'unknown')}-{tid}": "".join(traceback.format_stack(frame))
        for tid, frame in sys._current_frames().items()
    }


class TelemetryShipper:
    """Worker-side half of the federation: on a bounded cadence, export
    everything new since the last ship (journal via seq cursor, spans via
    seq cursor, metrics as a full render — registries are cheap and
    idempotent to re-ship) and hand the encoded TELEM body to ``send``.

    The shipper is pumped from the worker's existing frame loop — no
    thread of its own, so chaos replay stays deterministic and the
    perf-smoke twin-run can prove zero added host syncs."""

    def __init__(self, send, instance: str, *, clock=time.monotonic,
                 interval_s: float = 0.25,
                 budget_bytes: int = TELEM_BUDGET_BYTES,
                 journal=None, traces=None, registry=None):
        self._send = send
        self.instance = str(instance)
        self.clock = clock
        self.interval_s = float(interval_s)
        self.budget_bytes = int(budget_bytes)
        self._journal = journal if journal is not None else JOURNAL
        self._traces = traces if traces is not None else TRACES
        self._registry = registry if registry is not None else REGISTRY
        self._journal_cursor = 0
        self._span_cursor = 0
        self._last_ship = -float("inf")
        self.shipped_frames = 0
        self.shipped_bytes = 0
        self.last_frame_bytes = 0

    def _fit(self, doc: dict) -> bytes:
        """Shed sections until the encoded body fits the budget.  Order is
        deliberate: stacks are the biggest and least perishable (the next
        forced flush re-captures them), journal events and spans degrade
        oldest-first (the fleet ring already saw older cadence ships), and
        the metrics text goes last because it is the only section that
        cannot be reconstructed from earlier frames."""
        body = encode_telem(doc)
        if len(body) <= self.budget_bytes:
            return body
        doc = dict(doc)
        doc["truncated"] = True
        doc.pop("stacks", None)
        for key in ("journal", "spans"):
            body = encode_telem(doc)
            if len(body) <= self.budget_bytes:
                return body
            items = list(doc.get(key) or [])
            while items and len(body) > self.budget_bytes:
                items = items[max(1, len(items) // 2):]  # drop oldest half
                doc[key] = items
                body = encode_telem(doc)
        if len(body) > self.budget_bytes:
            doc["metrics"] = ""
            body = encode_telem(doc)
        return body

    def maybe_ship(self, force: bool = False, include_stacks: bool = False) -> int:
        """Ship one snapshot if the cadence (or ``force``) says so; returns
        the frame body size in bytes, 0 when the cadence held fire."""
        now = self.clock()
        if not force and now - self._last_ship < self.interval_s:
            return 0
        self._last_ship = now
        self._journal_cursor, events = self._journal.export_since(self._journal_cursor)
        self._span_cursor, spans = self._traces.export_since(self._span_cursor)
        doc = {
            "instance": self.instance,
            "mono": now,
            "journal": events,
            "spans": spans,
            "metrics": self._registry.render(),
        }
        if include_stacks:
            doc["stacks"] = _thread_stacks()
        body = self._fit(doc)
        self._send(body)
        self.shipped_frames += 1
        self.shipped_bytes += len(body)
        self.last_frame_bytes = len(body)
        _M_TELEM_FRAMES.inc(outcome=SHIPPED)
        _M_TELEM_BYTES.inc(len(body), direction=_TX)
        return len(body)


# ---------------------------------------------------------------------------
# Control-plane merger.
# ---------------------------------------------------------------------------

_FLEET_JOURNAL_CAP = 4096
_SPANS_PER_INSTANCE = 1024
_HOP_CTX_CAP = 512

SUPERVISOR = "supervisor"  # the control plane's own instance label


def _inject_instance_label(line: str, instance: str) -> str:
    """Rewrite one exposition sample line to carry ``instance="..."``.
    Handles both labeled (``name{k="v"} 1``) and bare (``name 1``) forms;
    the value is escaped so a hostile worker name cannot inject samples."""
    esc = escape_label_value(instance)
    if "{" in line:
        name, rest = line.split("{", 1)
        return name + '{instance="' + esc + '",' + rest
    name, _, value = line.partition(" ")
    return name + '{instance="' + esc + '"} ' + value


class FleetObservability:
    """Control-plane half of the federation: ingest TELEM snapshots from
    every worker, keep bounded per-instance state, and serve the merged
    fleet views.  Thread-safe — the DiagnosticsServer scrapes concurrently
    with the transport drain loops that ingest."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: dict[str, dict] = {}
        self._journal: deque[dict] = deque(maxlen=_FLEET_JOURNAL_CAP)
        self._hops: OrderedDict[int, dict] = OrderedDict()

    # -- ingestion -----------------------------------------------------

    def ingest_wire(self, instance: str, body: bytes,
                    clock_offset_s: float | None = None) -> bool:
        doc = decode_telem(body)
        if doc is None:
            JOURNAL.record("obs", "telem.drop", correlation=str(instance),
                           nbytes=len(body))
            return False
        _M_TELEM_BYTES.inc(len(body), direction=_RX)
        self.ingest(str(doc.get("instance") or instance), doc,
                    clock_offset_s=clock_offset_s)
        return True

    def ingest(self, instance: str, doc: dict,
               clock_offset_s: float | None = None) -> None:
        instance = str(instance)
        with self._lock:
            st = self._instances.setdefault(instance, {
                "spans": deque(maxlen=_SPANS_PER_INSTANCE),
                "metrics": "",
                "stacks": None,
                "offset_s": 0.0,
                "mono": 0.0,
                "frames": 0,
                "truncated": 0,
            })
            st["frames"] += 1
            st["mono"] = float(doc.get("mono", st["mono"]) or 0.0)
            if doc.get("truncated"):
                st["truncated"] += 1
            if clock_offset_s is not None:
                st["offset_s"] = float(clock_offset_s)
            metrics_text = doc.get("metrics")
            if metrics_text:
                st["metrics"] = str(metrics_text)
            if doc.get("stacks"):
                st["stacks"] = doc["stacks"]
            for span in doc.get("spans") or []:
                if isinstance(span, dict):
                    st["spans"].append(span)
            for event in doc.get("journal") or []:
                if isinstance(event, dict):
                    self._journal.append({**event, "instance": instance})
            n = len(self._instances)
        if doc.get("truncated"):
            _M_TELEM_TRUNCATED.inc()
        _M_TELEM_FRAMES.inc(outcome=INGESTED)
        _M_INSTANCES.set(n)

    # -- hop context / dead-hop attribution ----------------------------

    def note_hop(self, rid: int, trace_id: str, parent_id: str = "",
                 instance: str = "") -> None:
        """Remember which trace a request's in-flight hop belongs to, so a
        worker that dies mid-hop can still be attributed into the right
        span tree (the worker's own span for that hop died with it)."""
        with self._lock:
            self._hops[int(rid)] = {
                "trace_id": str(trace_id),
                "parent_id": str(parent_id),
                "instance": str(instance),
            }
            self._hops.move_to_end(int(rid))
            while len(self._hops) > _HOP_CTX_CAP:
                self._hops.popitem(last=False)

    def hop_ctx(self, rid: int) -> dict | None:
        with self._lock:
            ctx = self._hops.get(int(rid))
            return dict(ctx) if ctx else None

    def forget_hop(self, rid: int) -> None:
        with self._lock:
            self._hops.pop(int(rid), None)

    def attribute_dead_hop(self, rid: int, instance: str, reason: str = "",
                           traces=None) -> None:
        """Record a synthetic zero-width ``hop.dead`` span in the control
        plane's OWN trace buffer: the worker that owned the hop is gone,
        so whatever it flushed before death is all that federated — this
        span marks the gap and names the culprit instance."""
        ctx = self.hop_ctx(rid) or {}
        now = time.monotonic()
        (traces if traces is not None else TRACES).record(
            trace_id=ctx.get("trace_id") or f"req-{rid}",
            name="hop.dead",
            t0=now, t1=now,
            parent_id=ctx.get("parent_id", ""),
            instance=str(instance),
            reason=str(reason),
            request_id=int(rid),
        )
        JOURNAL.record(
            "obs", "hop.dead", correlation=f"req-{rid}",
            instance=str(instance), reason=str(reason),
        )
        self.forget_hop(rid)

    # -- fleet views ---------------------------------------------------

    def fleet_journal_doc(self, limit: int = 200, correlation: str | None = None,
                          component: str | None = None,
                          instance: str | None = None) -> dict:
        """Instance-tagged merge of every federated journal tail, ordered
        by each event's RAW epoch timestamp (``ts_s``) — wall clocks are
        close enough for journal ordering; spans get the real skew
        model."""
        with self._lock:
            events = list(self._journal)
            instances = sorted(self._instances)
        if correlation is not None:
            events = [e for e in events if e.get("correlation") == str(correlation)]
        if component is not None:
            events = [e for e in events if e.get("component") == component]
        if instance is not None:
            events = [e for e in events if e.get("instance") == instance]
        events.sort(key=lambda e: e.get("ts_s", 0.0))
        return {
            "instances": instances,
            "merged": len(events),
            "events": events[-int(limit):],
        }

    def _all_span_nodes(self, traces=None) -> list[dict]:
        nodes = []
        for doc in (traces if traces is not None else TRACES).snapshot(
                limit=_SPANS_PER_INSTANCE):
            nodes.append((SUPERVISOR, 0.0, doc))
        with self._lock:
            for name, st in self._instances.items():
                off = float(st.get("offset_s") or 0.0)
                for doc in st["spans"]:
                    nodes.append((name, off, doc))
        out = []
        for inst, off, doc in nodes:
            out.append({
                "trace_id": str(doc.get("trace_id", "")),
                "span_id": str(doc.get("span_id", "")),
                "parent_id": str(doc.get("parent_id", "")),
                "name": str(doc.get("name", "")),
                "instance": inst,
                # Skew normalization: offset is (instance_clock -
                # control_plane_clock), so subtracting maps the span into
                # the control plane's monotonic domain.
                "t0": float(doc.get("t0", 0.0)) - off,
                "t1": float(doc.get("t1", 0.0)) - off,
                "attrs": dict(doc.get("attrs", {}) or {}),
                "children": [],
            })
        return out

    def fleet_traces_doc(self, trace_id: str | None = None,
                         limit: int = 50, traces=None) -> dict:
        """Merged, skew-normalized span trees across every instance.  Tree
        structure comes from span_id/parent_id; spans whose parent never
        federated (dropped frame, dead worker) surface as extra roots of
        the same trace rather than vanishing."""
        nodes = self._all_span_nodes(traces=traces)
        by_trace: dict[str, list[dict]] = {}
        for n in nodes:
            tid = n["trace_id"]
            if trace_id is not None and tid != str(trace_id):
                continue
            by_trace.setdefault(tid, []).append(n)
        trees = []
        for tid, members in by_trace.items():
            by_id = {n["span_id"]: n for n in members if n["span_id"]}
            roots = []
            for n in members:
                parent = by_id.get(n["parent_id"]) if n["parent_id"] else None
                if parent is not None and parent is not n:
                    parent["children"].append(n)
                else:
                    roots.append(n)
            for n in members:
                n["children"].sort(key=lambda c: c["t0"])
            roots.sort(key=lambda c: c["t0"])
            trees.append({
                "trace_id": tid,
                "spans": len(members),
                "instances": sorted({n["instance"] for n in members}),
                "t0": min(n["t0"] for n in members),
                "t1": max(n["t1"] for n in members),
                "roots": roots,
            })
        trees.sort(key=lambda t: t["t0"], reverse=True)
        with self._lock:
            instances = sorted(self._instances)
        return {"instances": instances, "traces": trees[:int(limit)]}

    def render_federated(self, registry=None) -> str:
        """The control plane's own registry render, followed by every
        worker's latest snapshot rewritten under its ``instance=`` label.
        HELP/TYPE comments are kept only from the local render — the
        worker copies would duplicate them — and sample lines merge
        cleanly because the instance label disambiguates series."""
        local = (registry if registry is not None else REGISTRY).render()
        out = [local.rstrip("\n")]
        with self._lock:
            snapshots = sorted(
                (name, st["metrics"]) for name, st in self._instances.items()
            )
        for name, text in snapshots:
            for line in (text or "").splitlines():
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                out.append(_inject_instance_label(line, name))
        return "\n".join(out) + "\n"

    def bundle_doc(self, journal_limit: int = 100) -> dict:
        """Per-instance snapshot for diag bundles (mp_harness death
        reports, tools/diag_bundle.py --fleet): journals, metrics, stacks
        and federation freshness for every worker the plane has seen —
        including workers that are ALREADY DEAD, which is the whole
        point of a death report."""
        with self._lock:
            names = sorted(self._instances)
            states = {n: self._instances[n] for n in names}
            journal = list(self._journal)
        doc: dict = {"instances": {}}
        for name in names:
            st = states[name]
            doc["instances"][name] = {
                "frames": st["frames"],
                "truncated_frames": st["truncated"],
                "clock_offset_s": st["offset_s"],
                "spans_buffered": len(st["spans"]),
                "metrics": st["metrics"],
                "stacks": st["stacks"],
                "journal_tail": [
                    e for e in journal if e.get("instance") == name
                ][-int(journal_limit):],
            }
        return doc

    def stats(self) -> dict:
        with self._lock:
            return {
                "instances": sorted(self._instances),
                "journal_buffered": len(self._journal),
                "hops_tracked": len(self._hops),
            }

    def clear(self) -> None:
        with self._lock:
            self._instances.clear()
            self._journal.clear()
            self._hops.clear()
        _M_INSTANCES.set(0)


FLEET = FleetObservability()


# ---------------------------------------------------------------------------
# SLO burn-rate monitor.
# ---------------------------------------------------------------------------

DEFAULT_BURN_WINDOWS = (("5m", 300.0), ("1h", 3600.0))

# Closed tier vocabulary (workload.SloTier thresholds map onto it via
# classify_tier; "fleet" is the tier for histogram-derived fleet-wide
# observations where per-request tier identity is gone).
INTERACTIVE = "interactive"
STANDARD = "standard"
BATCH = "batch"
FLEET_TIER = "fleet"


class SloBurnRateMonitor:
    """Multi-window error-budget burn evaluator.

    ``observe(now, tier, ok)`` feeds per-request SLO verdicts (the same
    ok-vs-miss scoring ``workload.replay`` already computes from TTFT and
    TPOT); ``ingest_federated`` feeds fleet-wide verdicts derived by
    bucket-diffing each instance's federated ``tpu_serve_ttft_seconds``
    histogram.  ``tick(now)`` evaluates ``burn = miss_fraction /
    error_budget`` over every window × tier, publishes the gauges,
    journals fired/cleared transitions, and appends to a bounded timeline
    that ``bench.py serving_autoscale`` embeds as an artifact.

    Burn semantics: 1.0 means missing exactly the budgeted fraction (on
    pace to spend the whole budget over the window); the alert fires only
    when EVERY window agrees (the classic multi-window guard: the short
    window gives speed, the long window suppresses blips).  All clocks are
    the caller's — simulated time in bench/replay, monotonic in live
    processes."""

    def __init__(self, *, error_budget: float = 0.05,
                 windows=DEFAULT_BURN_WINDOWS,
                 alert_threshold: float = 2.0,
                 slice_s: float = 5.0,
                 timeline_every_s: float = 30.0,
                 timeline_cap: int = 512,
                 journal=None):
        self.error_budget = max(1e-6, float(error_budget))
        self.windows = tuple((str(n), float(s)) for n, s in windows)
        self.alert_threshold = float(alert_threshold)
        self.slice_s = max(1e-3, float(slice_s))
        self.timeline_every_s = float(timeline_every_s)
        self._journal = journal if journal is not None else JOURNAL
        self._lock = threading.Lock()
        self._slices: dict[int, dict[str, list[int]]] = {}  # idx -> tier -> [ok, miss]
        self._hist_cursors: dict[tuple, float] = {}
        self._alerting: set[str] = set()
        self._last_burn: dict[str, dict[str, float]] = {}
        self._timeline: deque[dict] = deque(maxlen=int(timeline_cap))
        self._last_sample = -float("inf")
        self._transitions = 0

    @staticmethod
    def classify_tier(ttft_slo_s: float) -> str:
        """Map a request's TTFT SLO bound onto the closed tier vocabulary
        (workload's default tiers: interactive 1.0s / standard 3.0s /
        batch 10.0s)."""
        if ttft_slo_s <= 1.0:
            return INTERACTIVE
        if ttft_slo_s <= 3.0:
            return STANDARD
        return BATCH

    def observe(self, now: float, tier: str, ok: bool, count: int = 1) -> None:
        idx = int(now // self.slice_s)
        with self._lock:
            counts = self._slices.setdefault(idx, {}).setdefault(str(tier), [0, 0])
            counts[0 if ok else 1] += int(count)

    def ingest_federated(self, now: float, fleet: FleetObservability | None = None,
                         slo_s: float = 1.0, tier: str = FLEET_TIER) -> int:
        """Derive fleet-wide verdicts from the federated TTFT histograms:
        per instance, the delta of ``tpu_serve_ttft_seconds`` cumulative
        counts since the last ingest, with the largest bucket bound ≤
        ``slo_s`` as the ok/miss split.  Bucket-diffing cumulative
        counters makes re-ingest idempotent across federation cadences."""
        from ..utils.metrics import parse_prom_text  # utils-only; cheap
        fleet = fleet if fleet is not None else FLEET
        with fleet._lock:
            snapshots = [
                (name, st["metrics"]) for name, st in fleet._instances.items()
            ]
        observed = 0
        for name, text in snapshots:
            if not text:
                continue
            try:
                parsed = parse_prom_text(text)
            except (ValueError, IndexError):
                continue
            buckets = parsed.get("tpu_serve_ttft_seconds_bucket", {})
            totals: dict[tuple, float] = {}
            ok_counts: dict[tuple, float] = {}
            for labels, value in buckets.items():
                le = dict(labels).get("le", "")
                rest = tuple(kv for kv in labels if kv[0] != "le")
                if le == "+Inf":
                    totals[rest] = value
                else:
                    try:
                        bound = float(le)
                    except ValueError:
                        continue
                    if bound <= slo_s:
                        ok_counts[rest] = max(ok_counts.get(rest, 0.0), value)
            for rest, total in totals.items():
                ok = ok_counts.get(rest, 0.0)
                key = (name, rest, "total")
                ok_key = (name, rest, "ok")
                d_total = total - self._hist_cursors.get(key, 0.0)
                d_ok = ok - self._hist_cursors.get(ok_key, 0.0)
                self._hist_cursors[key] = total
                self._hist_cursors[ok_key] = ok
                d_total, d_ok = max(0.0, d_total), max(0.0, min(d_ok, d_total))
                miss = d_total - d_ok
                if d_ok:
                    self.observe(now, tier, True, count=int(round(d_ok)))
                if miss:
                    self.observe(now, tier, False, count=int(round(miss)))
                observed += int(round(d_total))
        return observed

    def _window_counts(self, now: float, span_s: float) -> dict[str, list[int]]:
        lo = now - span_s
        out: dict[str, list[int]] = {}
        for idx, tiers in self._slices.items():
            t = idx * self.slice_s
            if t <= now and t > lo - self.slice_s:
                for tier, (ok, miss) in tiers.items():
                    agg = out.setdefault(tier, [0, 0])
                    agg[0] += ok
                    agg[1] += miss
        return out

    def tick(self, now: float) -> dict:
        """Evaluate every window, publish gauges, journal transitions,
        sample the timeline.  Returns the burn map for callers that want
        the numbers without re-reading gauges."""
        with self._lock:
            horizon = now - max(s for _, s in self.windows) - self.slice_s
            for idx in [i for i in self._slices if i * self.slice_s < horizon]:
                del self._slices[idx]
            per_window = {
                name: self._window_counts(now, span)
                for name, span in self.windows
            }
        burn: dict[str, dict[str, float]] = {}
        tiers = set()
        for counts in per_window.values():
            tiers.update(counts)
        for tier in sorted(tiers):
            burn[tier] = {}
            for window, _span in self.windows:
                ok, miss = per_window[window].get(tier, (0, 0))
                total = ok + miss
                rate = (miss / total / self.error_budget) if total else 0.0
                burn[tier][window] = rate
                _M_BURN.set(rate, window=window, tier=tier)
        now_alerting = {
            tier for tier, by_window in burn.items()
            if by_window and all(
                r > self.alert_threshold for r in by_window.values()
            )
        }
        for tier in sorted(now_alerting - self._alerting):
            self._transitions += 1
            _M_BURN_ALERT.set(1.0, tier=tier)
            self._journal.record(
                "obs", "slo.burn.fired", correlation=f"slo-{tier}",
                burn={w: round(r, 4) for w, r in burn[tier].items()},
                threshold=self.alert_threshold,
            )
        for tier in sorted(self._alerting - now_alerting):
            self._transitions += 1
            _M_BURN_ALERT.set(0.0, tier=tier)
            self._journal.record(
                "obs", "slo.burn.cleared", correlation=f"slo-{tier}",
                burn={w: round(r, 4) for w, r in burn.get(tier, {}).items()},
            )
        self._alerting = now_alerting
        self._last_burn = burn
        if now - self._last_sample >= self.timeline_every_s:
            self._last_sample = now
            self._timeline.append({
                "t": round(now, 3),
                "burn": {
                    tier: {w: round(r, 4) for w, r in by_window.items()}
                    for tier, by_window in burn.items()
                },
                "alerting": sorted(now_alerting),
            })
        return burn

    @property
    def alerting(self) -> bool:
        return bool(self._alerting)

    @property
    def alerting_tiers(self) -> list[str]:
        return sorted(self._alerting)

    def timeline(self) -> list[dict]:
        return list(self._timeline)

    def stats(self) -> dict:
        return {
            "alerting": sorted(self._alerting),
            "burn": self._last_burn,
            "windows": [name for name, _ in self.windows],
            "error_budget": self.error_budget,
            "alert_threshold": self.alert_threshold,
            "timeline_samples": len(self._timeline),
            "transitions": self._transitions,
        }


def debug_obs_doc() -> dict:
    """Shape behind ``/debug/fleet-traces``' sibling summary and diag
    bundles: the plane's own health, not the federated payloads."""
    return {
        "fleet": FLEET.stats(),
        "traces": TRACES.stats(),
        "budget_bytes": TELEM_BUDGET_BYTES,
    }
