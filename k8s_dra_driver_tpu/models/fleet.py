"""Fleet router: N interchangeable engine replicas behind one front door.

PRs 4–6 built the single-engine primitives — continuous batching
(``pump``), per-request failure domains (typed Completions, quarantine,
shedding), bit-equal ``snapshot_active()``/``restore()`` across engine
kinds, and the ``EngineStats`` load-signal contract.  This module
composes them into the cluster-scale layer ROADMAP item 1 calls for: a
:class:`FleetRouter` that owns FLEET-level failure domains, so when one
replica of N degrades, exactly that replica's blast radius stays
contained.

Three responsibilities:

* **Health-gated routing.**  Per-replica health is derived from
  ``EngineStats`` (burst progress vs resident slots, stats-feed
  freshness, quarantine tally, watchdog heartbeat age) and drives a
  per-replica :class:`~k8s_dra_driver_tpu.utils.retry.CircuitBreaker`
  (endpoint ``fleet/<name>`` — the breaker's own gauge/journal wiring
  comes free).  A wedged or quarantine-heavy replica stops receiving
  admissions while survivors keep serving.  Replica states:
  ``healthy → suspect → evacuating → drained`` (ARCHITECTURE.md "Fleet
  failure domains" has the diagram); a suspect replica that recovers
  returns to healthy.

* **Live-migration evacuation.**  A degraded or draining replica is
  evacuated with ``snapshot_active()`` → ``restore(..., merge=True)``
  onto healthy replicas — cross-engine-kind, bit-equal, and the
  telemetry traces keep one contiguous timeline (PR 6).  The source's
  slots/blocks are then freed WITHOUT completions
  (``release_active()``), so nothing double-delivers and the dead
  replica's block accounting still balances.  Entries beyond current
  fleet capacity park at the router and restore as capacity frees.  One
  journal correlation id (``evac-N``) spans
  suspect → snapshot → restore → resumed.

* **Fleet-level admission.**  One front-door queue with fleet deadline
  budgets (per-request ``admission_deadline_s``) and bounded-queue
  shedding: overflow is rejected newest-first as typed ``status="shed"``
  Completions whose ``retry_after_s`` is FLEET-wide (queue depth × mean
  live-replica step latency ÷ live replicas — the whole fleet drains in
  parallel).  Placement is least-loaded (free slots, then free blocks)
  with prefix-cache and LoRA-adapter affinity bonuses scored from
  ``EngineStats`` and the router's routing history.

The router is deliberately host-only: every decision is dict/clock work
over ``stats()`` snapshots, and routed requests dispatch exactly the
device work a bare engine would (pinned by
``tools/perf_smoke.py check_router_overhead``).  Replica engines are
anything satisfying the :class:`Engine` protocol — the formal contract
extracted from ``models/serve.py`` + ``models/paged.py`` and pinned by
the conformance matrix in ``tests/test_fleet.py``.

Replica id ranges: each replica's engine is seeded a disjoint
``request_id`` range (``i * ID_STRIDE`` via an empty merge-restore), so
ids stay fleet-unique and an evacuated stream can never collide with a
target engine's own ids.

This module stays importable without jax (the engines bring jax; the
router itself never does) so ``/debug/fleet`` can render from
control-plane binaries.
"""

from __future__ import annotations

import inspect
import os
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Protocol, runtime_checkable

from k8s_dra_driver_tpu.models.telemetry import EngineStats, terminal_retirer
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from k8s_dra_driver_tpu.utils.retry import CircuitBreaker
from k8s_dra_driver_tpu.utils.watchdog import WATCHDOG

_M_REPLICAS = REGISTRY.gauge(
    "tpu_fleet_replicas",
    "fleet replicas by health state (healthy/suspect/evacuating/drained)",
)
_M_EVAC = REGISTRY.counter(
    "tpu_fleet_evacuations_total",
    "replica evacuations, by triggering reason",
)
_M_FLEET_SHED = REGISTRY.counter(
    "tpu_fleet_shed_total",
    "requests shed at the fleet front door (queue overflow or admission deadline)",
)
_M_FLEET_QUEUE = REGISTRY.gauge(
    "tpu_fleet_queue_depth",
    "requests waiting in the fleet front-door queue",
)

# Replica health states — the router's failure-domain lifecycle.
HEALTHY = "healthy"
SUSPECT = "suspect"
EVACUATING = "evacuating"
DRAINED = "drained"
STATES = (HEALTHY, SUSPECT, EVACUATING, DRAINED)

# Disjoint request-id range seeded per replica: evacuated streams keep
# their ids in the target engine, so ids must be fleet-unique by
# construction, not by luck.
ID_STRIDE = 1_000_000

_SEQ_LOCK = threading.Lock()
_SEQ = 0


def _next_seq() -> int:
    global _SEQ
    with _SEQ_LOCK:
        _SEQ += 1
        return _SEQ


@runtime_checkable
class Engine(Protocol):
    """The formal replica contract extracted from ``ServeEngine`` (dense)
    and ``PagedServeEngine`` (paged).  Anything satisfying it is an
    interchangeable unit behind the router: same admission surface, same
    typed Completion vocabulary (``serve.TERMINAL_STATUSES``), same
    ``EngineStats`` load signal, and the snapshot/restore/release triple
    that makes live migration possible.  ``tests/test_fleet.py`` pins
    both engine classes against it (structure AND signatures — a
    runtime_checkable Protocol only checks member presence)."""

    n_slots: int
    sync_interval: int

    def free_slots(self) -> int: ...

    def submit(self, prompt, max_tokens, **kwargs) -> int: ...

    def step_burst(self) -> int: ...

    def pump(self, requests, max_steps=100_000, queue_limit=None) -> list: ...

    def completions(self) -> list: ...

    def cancel(self, request_id: int) -> bool: ...

    def snapshot_active(self) -> dict: ...

    def restore(self, snapshot: dict, merge: bool = False) -> list: ...

    def release_active(self) -> int: ...

    def stats(self) -> EngineStats: ...


@dataclass(frozen=True)
class FleetPolicy:
    """Health/placement thresholds — all host-side, all deterministic.

    The suspect detectors are TICK-counted (router pump iterations), not
    wall-clocked, so chaos tests converge in milliseconds and production
    behavior scales with actual serving cadence.  ``heartbeat_suspect_s``
    is the wall-clock backstop for routers driven slower than their
    engines (an engine whose ``EngineStats.heartbeat_age_s`` grows past
    it while holding residents is wedged regardless of tick counts)."""

    stall_suspect_ticks: int = 3      # resident slots but no burst progress
    stale_stats_ticks: int = 3        # identical stats snapshots in a row
    quarantine_suspect: int = 2       # quarantine tally that marks a replica
    heartbeat_suspect_s: float = 30.0
    breaker_failures: int = 3         # unhealthy verdicts to open the breaker
    breaker_reset_s: float = 30.0
    auto_evacuate: bool = True        # evacuate when a replica's breaker opens
    affinity_prefix: int = 8          # leading tokens forming the prefix key
    # Affinity must beat a one-slot load imbalance (a warm prefix cache
    # saves a whole prefill) but lose to two — least-loaded still wins
    # when the spread is real.
    affinity_bonus: float = 1.25
    max_affinity_entries: int = 1024  # bound on the routing-history maps
    # Fleet prefix-cache tier (models/fleet_prefix.py): when a prefix
    # index is attached, depth-aware affinity replaces the flat bonus —
    # every whole cached block the candidate owns earns
    # ``prefix_depth_bonus_per_block``, capped.  0.6/block keeps the same
    # shape as the flat bonus but proportional: one cached block still
    # loses to a one-slot load imbalance, two blocks beat one slot, four
    # beat two — a deeper cached prefix wins a proportionally larger
    # imbalance, never an unbounded one.
    prefix_depth_bonus_per_block: float = 0.6
    prefix_depth_bonus_max: float = 4.0


class Replica:
    """One engine behind the router: its health state, breaker, cached
    load signal and the counters the suspect detectors run on."""

    def __init__(self, name: str, engine: Engine, policy: FleetPolicy, clock):
        self.name = name
        self.engine = engine
        self.state = HEALTHY
        self.breaker = CircuitBreaker(
            endpoint=f"fleet/{name}",
            failure_threshold=policy.breaker_failures,
            reset_timeout_s=policy.breaker_reset_s,
            clock=clock,
        )
        self.last_stats: EngineStats | None = None
        self.stalled_ticks = 0
        self.stale_ticks = 0
        self.evacuations = 0
        self.last_verdict = HEALTHY  # why-string for /debug/fleet
        self.evac_corr = ""          # journal correlation spanning one evacuation
        # submit() kwarg surface, computed once: the router passes through
        # only what this replica kind accepts (e.g. ``priority`` exists on
        # the paged engine, not the dense one).
        self.submit_params = frozenset(
            inspect.signature(engine.submit).parameters
        )

    def resident(self) -> int:
        eng = self.engine
        return (
            (eng.n_slots - eng.free_slots())
            + len(getattr(eng, "_preempted", ()) or ())
        )

    def idle(self) -> bool:
        eng = self.engine
        return (
            eng.free_slots() == eng.n_slots
            and not getattr(eng, "_admitting", ())
            and not getattr(eng, "_preempted", ())
        )

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "state": self.state,
            "breaker": self.breaker.state,
            "verdict": self.last_verdict,
            "evacuations": self.evacuations,
            "stats": self.last_stats.to_json() if self.last_stats else None,
        }


class FleetRouter:
    """The fleet front door.  Single-loop like the engines it drives:
    admission, health verdicts, evacuation and burst-stepping all run on
    the caller's thread inside :meth:`pump` ticks (or explicit
    :meth:`submit`/:meth:`drain` calls between pumps)."""

    def __init__(
        self,
        engines=(),
        policy: FleetPolicy | None = None,
        fault_injector=None,
        clock=time.monotonic,
    ):
        self.policy = policy or FleetPolicy()
        self.clock = clock
        self.seq = _next_seq()
        self.replicas: list[Replica] = []
        self.fault_injector = fault_injector
        if self.fault_injector is None:
            from k8s_dra_driver_tpu.utils import faults

            raw = os.environ.get(faults.ENV_VAR, "")
            if raw:
                self.fault_injector = faults.FaultInjector.from_env(raw)
        self._owner: dict[int, Replica] = {}  # request_id -> serving replica
        self._parked: list[dict] = []  # evacuated entries awaiting capacity
        self._completions: list = []
        self._prefix_home: dict[tuple, str] = {}
        self._adapter_home: dict[int, str] = {}
        self._next_stride = 0
        self._evac_seq = 0
        self._tick = 0
        self._queue_depth = 0
        self.shed_count = 0
        self.last_shed = None
        # Host-side observers invoked once per router tick (pump iteration
        # or explicit tick()) after health verdicts and admission settle —
        # the drive surface models/autoscaler.py attaches to.  Hooks must
        # be cheap and must not dispatch device work (the perf-smoke
        # autoscaler guard pins that).
        self.tick_hooks: list = []
        # Fleet prefix-cache tier (models/fleet_prefix.py): both default
        # None — routing/scoring behavior is byte-identical until a tier
        # or index is attached.
        self.prefix_tier = None
        self.prefix_index = None
        for item in engines:
            if isinstance(item, tuple):
                name, engine = item
                self.add_replica(engine, name=name)
            else:
                self.add_replica(item)
        _LIVE_ROUTERS.add(self)

    # -- fleet membership ----------------------------------------------------

    def add_replica(self, engine, name: str | None = None) -> Replica:
        """Register an engine as a replica: protocol-check it, seed it a
        disjoint request-id range (through the public restore() surface —
        an empty merge-restore only bumps ``next_id``), and open it for
        admissions."""
        if not isinstance(engine, Engine):
            missing = [
                m for m in (
                    "free_slots", "submit", "step_burst", "pump", "completions",
                    "cancel", "snapshot_active", "restore", "release_active",
                    "stats",
                )
                if not callable(getattr(engine, m, None))
            ]
            raise TypeError(
                f"{type(engine).__name__} does not satisfy the Engine "
                f"protocol (missing: {missing or 'attributes'})"
            )
        name = name or f"r{len(self.replicas)}"
        if any(r.name == name for r in self.replicas):
            raise ValueError(f"duplicate replica name {name!r}")
        rep = Replica(name, engine, self.policy, self.clock)
        base = self._next_stride * ID_STRIDE
        self._next_stride += 1
        engine.restore(
            {"engine": type(engine).__name__, "next_id": base, "requests": []},
            merge=True,
        )
        self.replicas.append(rep)
        if self.prefix_tier is not None:
            self.prefix_tier.bind_engine(rep.name, rep.engine)
        JOURNAL.record(
            "fleet", "replica.add", correlation=name,
            engine=type(engine).__name__, n_slots=engine.n_slots,
            id_base=base,
        )
        self._publish_states()
        return rep

    def attach_prefix_index(self, index) -> None:
        """Depth-aware prefix scoring only (no pull machinery) — what the
        workload simulator uses: engines consult/publish the index
        themselves and the router just routes-to-home by cached depth."""
        self.prefix_index = index

    def attach_prefix_tier(self, tier) -> None:
        """Full fleet prefix-cache tier: depth-aware scoring, engine
        publish hooks, and admission-time remote pulls (tier.prepare runs
        inside ``_submit_to``).  The tier's TTL sweep rides the tick hooks
        (host-only dict work — no device dispatch, per the tick_hooks
        contract)."""
        self.prefix_tier = tier
        self.prefix_index = tier.index
        for rep in self.replicas:
            tier.bind_engine(rep.name, rep.engine)
        self.tick_hooks.append(tier.tick)

    def replica(self, name: str) -> Replica:
        for rep in self.replicas:
            if rep.name == name:
                return rep
        raise KeyError(f"no replica named {name!r}")

    def admittable_replicas(self) -> list[Replica]:
        """Replicas that can take NEW work right now: healthy state and a
        breaker that is not open.  This is the live denominator for every
        fleet-wide admission hint (shed retry-after, autoscaler
        utilization): draining/evacuating/drained replicas are out, and a
        freshly added replica counts immediately — even before its first
        health tick populates ``last_stats``."""
        return [
            r for r in self.replicas
            if r.state == HEALTHY and r.breaker.state != CircuitBreaker.OPEN
        ]

    # -- admission -----------------------------------------------------------

    def submit(self, prompt, max_tokens: int, **kwargs) -> int:
        """Route one request immediately: health-gated, least-loaded,
        affinity-scored.  Raises RuntimeError when no admittable replica
        has capacity (callers queue upstream via :meth:`pump`, same
        contract as a bare engine's submit)."""
        req = {"prompt": list(prompt), "max_tokens": max_tokens, **kwargs}
        last_err: Exception | None = None
        for rep in self._candidates(
            req["prompt"], int(req.get("adapter", 0)), req
        ):
            try:
                return self._submit_to(rep, req)
            except RuntimeError as exc:  # capacity race (e.g. out of blocks)
                last_err = exc
                continue
        raise last_err or RuntimeError("no admittable replica with capacity")

    def _candidates(self, prompt, adapter: int, req: dict | None = None) -> list[Replica]:
        """Admittable replicas, best placement first.  Gate: state
        ``healthy`` AND the breaker admits (suspect/evacuating/drained
        replicas take no new work).  Score: free slots dominate (least
        loaded), free blocks break slot ties on paged replicas, and the
        prefix/adapter home earns ``affinity_bonus``.  With a fleet
        prefix index attached, the flat prefix bonus gives way to
        depth-aware scoring: each whole cached block the candidate owns
        earns ``prefix_depth_bonus_per_block`` (capped), so a deeper
        cached prefix beats a proportionally larger load imbalance."""
        pkey = self._prefix_key(prompt)
        survey = None
        if self.prefix_index is not None:
            chain = req.get("prefix_chain") if req else None
            if chain is None:
                chain = self.prefix_index.chain_for_tokens(prompt, adapter)
            survey = self.prefix_index.survey(chain, adapter)
        scored = []
        for idx, rep in enumerate(self.replicas):
            if rep.state != HEALTHY or not rep.breaker.allow():
                continue
            free = rep.engine.free_slots()
            if free <= 0:
                continue
            score = float(free)
            st = rep.last_stats
            if st is not None and st.free_blocks is not None:
                score += min(0.99, st.free_blocks / (100.0 * max(1, st.n_slots)))
            if survey is not None:
                owned = survey.get(rep.name)
                if owned is not None and (
                    self.prefix_tier is None
                    or self.prefix_tier.owner_available(rep.name)
                ):
                    # Breaker-open/dead owners earn no depth bonus:
                    # placement degrades to plain load balance (local-only)
                    # instead of chasing an unreachable cache.
                    score += min(
                        self.policy.prefix_depth_bonus_max,
                        self.policy.prefix_depth_bonus_per_block * owned[1],
                    )
            elif pkey is not None and self._prefix_home.get(pkey) == rep.name:
                score += self.policy.affinity_bonus
            if adapter and self._adapter_home.get(adapter) == rep.name:
                score += self.policy.affinity_bonus
            scored.append((-score, idx, rep))
        scored.sort(key=lambda t: t[:2])
        return [rep for _, _, rep in scored]

    def _prefix_key(self, prompt) -> tuple | None:
        n = self.policy.affinity_prefix
        return tuple(prompt[:n]) if len(prompt) >= n else None

    def _submit_to(self, rep: Replica, req: dict) -> int:
        kw = {
            k: v for k, v in req.items()
            if k in rep.submit_params and not k.startswith("_")
        }
        if "queued_at" in rep.submit_params:
            kw.setdefault("queued_at", req.get("_enqueued_at"))
        if self.prefix_tier is not None:
            # Warm the chosen replica before admission: local hit, remote
            # pull-and-inject, or nothing (cold).  prepare() contains its
            # own failures — the tier can cost an admission, never fail it.
            self.prefix_tier.prepare(
                rep.name, rep.engine, req["prompt"],
                max_tokens=req.get("max_tokens"),
                adapter=int(req.get("adapter", 0)),
                chain=req.get("prefix_chain"),
            )
        rid = rep.engine.submit(**kw)
        self._owner[rid] = rep
        pkey = self._prefix_key(req["prompt"])
        if pkey is not None:
            self._remember(self._prefix_home, pkey, rep.name)
        adapter = int(req.get("adapter", 0))
        if adapter:
            self._remember(self._adapter_home, adapter, rep.name)
        JOURNAL.record_lazy(
            "fleet", "request.route", correlation=f"req-{rid}",
            attrs=lambda: dict(replica=rep.name, prompt_len=len(req["prompt"])),
        )
        return rid

    def _remember(self, home: dict, key, name: str) -> None:
        home.pop(key, None)
        home[key] = name  # re-insert: dict order is the LRU order
        while len(home) > self.policy.max_affinity_entries:
            home.pop(next(iter(home)))

    # -- the fleet pump --------------------------------------------------------

    def pump(self, requests, max_steps: int = 100_000,
             queue_limit: int | None = None) -> list:
        """Fleet-level continuous batching: one front-door FIFO queue
        admitted across every healthy replica, burst-stepping all of them
        between admissions; returns every completion (typed, fleet-wide)
        that finished during the pump.

        ``queue_limit`` bounds the WAITING queue — overflow sheds
        newest-first with a fleet-wide retry-after.  Requests may carry
        ``admission_deadline_s`` (the fleet deadline budget): a request
        still queued when its budget lapses is shed instead of waiting
        forever.  Health verdicts, breaker updates and evacuations run
        every tick, so a replica that dies MID-PUMP is evacuated and its
        streams finish on survivors inside the same call."""
        queue = [self._normalize(r) for r in requests]
        t_enq = self.clock()
        for q in queue:
            q.setdefault("_enqueued_at", t_enq)
        out: list = []
        with WATCHDOG.guard("fleet.pump") as hb:
            for _ in range(max_steps):
                self._tick += 1
                progressed = self._health_tick()
                progressed |= self._replay_parked() > 0
                self._expire_queue(queue)
                admitted = self._admit(queue)
                if queue_limit is not None:
                    while len(queue) > queue_limit:
                        self._fleet_shed(
                            queue.pop(), len(queue) + 1,
                            f"admission queue full (limit {queue_limit})",
                        )
                self._queue_depth = len(queue)
                _M_FLEET_QUEUE.set(len(queue))
                hb.correlation = (
                    f"queue_depth={len(queue)} parked={len(self._parked)} "
                    f"sheds={self.shed_count}"
                )
                hb.beat()
                for hook in self.tick_hooks:
                    hook()
                stepped = self._step_replicas()
                out.extend(self.completions())
                live = [r for r in self.replicas if r.state != DRAINED]
                if (
                    not queue
                    and not self._parked
                    and all(r.idle() for r in live)
                ):
                    self._queue_depth = 0
                    _M_FLEET_QUEUE.set(0)
                    return out
                if not live:
                    raise self._wedge(
                        "fleet exhausted: every replica drained with work "
                        "still pending", queue,
                    )
                if (
                    stepped == 0 and admitted == 0 and not progressed
                    and all(r.resident() == 0 for r in live)
                    and not any(getattr(r.engine, "_admitting", ()) for r in live)
                ):
                    raise self._wedge(
                        "fleet pump wedged: waiting requests, no admittable "
                        "capacity, no progress", queue,
                    )
            raise self._wedge(
                f"fleet pump did not drain in {max_steps} ticks", queue
            )

    def _normalize(self, req) -> dict:
        if isinstance(req, dict):
            out = dict(req)
            out["prompt"] = list(out["prompt"])
            return out
        prompt, max_tokens = req
        return {"prompt": list(prompt), "max_tokens": max_tokens}

    def _admit(self, queue: list) -> int:
        admitted = 0
        while queue:
            req = queue[0]
            placed = False
            for rep in self._candidates(
                req["prompt"], int(req.get("adapter", 0)), req
            ):
                try:
                    self._submit_to(rep, req)
                except RuntimeError:
                    continue  # capacity race on this replica; try the next
                placed = True
                break
            if not placed:
                break  # FIFO: the head waits, nothing jumps it
            queue.pop(0)
            admitted += 1
        return admitted

    def _expire_queue(self, queue: list) -> None:
        """The fleet deadline budget: shed queued requests whose
        ``admission_deadline_s`` lapsed before a replica could take them."""
        now = self.clock()
        for idx in range(len(queue) - 1, -1, -1):
            budget = queue[idx].get("admission_deadline_s")
            if budget is None:
                continue
            if now - queue[idx]["_enqueued_at"] >= budget:
                self._fleet_shed(
                    queue.pop(idx), len(queue) + 1,
                    f"admission deadline {budget}s exceeded",
                )

    @terminal_retirer
    def _fleet_shed(self, req: dict, depth: int, why: str) -> None:
        """Typed fleet-level shed: the Completion carries a FLEET-wide
        retry-after — queue depth times the mean live-replica step
        latency, divided by the live replica count (the fleet drains in
        parallel, so the estimate must not be N times too pessimistic)."""
        from k8s_dra_driver_tpu.models.serve import Completion, ShedError

        # Denominator = replicas that can actually absorb the backlog.
        # Draining/evacuating replicas and open breakers are excluded (an
        # in-flight scale-down must not promise drain parallelism it no
        # longer has), while a just-added replica with no stats yet counts
        # — its step estimate simply falls back to the fleet mean.
        admittable = self.admittable_replicas()
        n_live = max(1, len(admittable))
        steps = [
            r.last_stats.last_step_s
            for r in admittable
            if r.last_stats is not None
        ]
        step_s = max(sum(steps) / len(steps) if steps else 0.0, 1e-3)
        retry_after = round(max(0.05, depth * step_s / n_live), 3)
        err = ShedError(
            f"fleet shed: {why} ({depth} waiting across {n_live} live "
            f"replica(s)); retry after {retry_after}s",
            retry_after,
        )
        self.shed_count += 1
        self.last_shed = err
        _M_FLEET_SHED.inc()
        JOURNAL.record(
            "fleet", "request.shed", depth=depth, reason=why,
            retry_after_s=retry_after,
        )
        self._completions.append(Completion(
            request_id=-1, tokens=list(req["prompt"]), generated=[],
            status="shed", error=str(err),
        ))

    def _step_replicas(self) -> int:
        """One burst per live replica, fault hooks consulted pre-dispatch
        (a crash fires BEFORE the burst, so the dead replica's host state
        is still snapshot-consistent — the same pre-mutation discipline as
        the engines' StepFaults)."""
        from k8s_dra_driver_tpu.utils.faults import ReplicaCrash

        stepped = 0
        for idx, rep in enumerate(self.replicas):
            if rep.state in (DRAINED, EVACUATING):
                continue
            inj = self.fault_injector
            if inj is not None:
                try:
                    inj.maybe_crash_replica(idx, self._tick)
                except ReplicaCrash as exc:
                    self._on_replica_death(rep, "replica_crash", str(exc))
                    continue
                if inj.take_replica_wedge(idx, self._tick):
                    continue  # a hung device: no burst, no progress
            try:
                stepped += rep.engine.step_burst()
            except RuntimeError as exc:
                # The engine failed its own wedge/poison limit mid-burst:
                # its quarantined slot already retired and the remaining
                # host state is consistent, so evacuate the survivors.
                self._on_replica_death(rep, "engine_error", str(exc))
                continue
            self._collect(rep)
        return stepped

    def _collect(self, rep: Replica) -> None:
        for c in rep.engine.completions():
            self._owner.pop(c.request_id, None)
            self._completions.append(c)

    def completions(self) -> list:
        out, self._completions = self._completions, []
        return out

    def cancel(self, request_id: int) -> bool:
        """Fleet-wide cancel: routed to whichever replica serves the id
        (ownership tracks migrations).  Only ADMITTED ids are cancellable —
        a request still in the front-door queue has no id yet."""
        rep = self._owner.get(request_id)
        if rep is None:
            return False
        ok = rep.engine.cancel(request_id)
        self._collect(rep)
        if ok:
            self._owner.pop(request_id, None)
        return ok

    # -- health --------------------------------------------------------------

    def _read_stats(self, idx: int, rep: Replica) -> EngineStats:
        inj = self.fault_injector
        if (
            inj is not None
            and rep.last_stats is not None
            and inj.take_stats_stale(idx, self._tick)
        ):
            return rep.last_stats  # the frozen feed the detector must catch
        return rep.engine.stats()

    def _health_tick(self) -> bool:
        """One verdict per live replica per tick; verdicts drive the
        breaker, the breaker drives state, an open breaker triggers
        evacuation.  Returns whether any state machinery advanced (the
        pump's no-progress detector must not fire while detection or
        recovery is still converging)."""
        changed = False
        for idx, rep in enumerate(self.replicas):
            if rep.state in (DRAINED, EVACUATING):
                continue
            st = self._read_stats(idx, rep)
            prev = rep.last_stats
            # Stale-feed detector: uptime_s strictly advances in any fresh
            # read, so an unchanged uptime means the feed is frozen and
            # the router cannot confirm this replica's health.
            if prev is not None and st.uptime_s <= prev.uptime_s:
                rep.stale_ticks += 1
            else:
                rep.stale_ticks = 0
            # Stall detector: resident streams but no burst progress.
            resident = st.resident_slots + st.admitting + st.preempted
            if prev is not None and resident > 0 and st.bursts <= prev.bursts:
                rep.stalled_ticks += 1
            else:
                rep.stalled_ticks = 0
            rep.last_stats = st
            verdict = self._verdict(rep, st, resident)
            if verdict != rep.last_verdict:
                rep.last_verdict = verdict
                changed = True
            if verdict == HEALTHY:
                rep.breaker.on_success()
                if rep.state == SUSPECT:
                    self._set_state(rep, HEALTHY, "recovered")
                    JOURNAL.record(
                        "fleet", "replica.recovered",
                        correlation=rep.evac_corr or rep.name,
                        replica=rep.name,
                    )
                    rep.evac_corr = ""
                    changed = True
                continue
            rep.breaker.on_failure()
            if rep.state == HEALTHY:
                rep.evac_corr = self._mint_corr()
                self._set_state(rep, SUSPECT, verdict)
                changed = True
            if (
                rep.state == SUSPECT
                and rep.breaker.state == CircuitBreaker.OPEN
                and self.policy.auto_evacuate
            ):
                self._evacuate(rep, verdict)
                changed = True
        return changed

    def _verdict(self, rep: Replica, st: EngineStats, resident: int) -> str:
        p = self.policy
        if rep.stale_ticks >= p.stale_stats_ticks:
            return "stats_stale"
        if resident > 0 and rep.stalled_ticks >= p.stall_suspect_ticks:
            return "wedged"
        if resident > 0 and st.heartbeat_age_s > p.heartbeat_suspect_s:
            return "wedged"
        if st.quarantined >= p.quarantine_suspect:
            return "quarantine_storm"
        return HEALTHY

    # -- evacuation ----------------------------------------------------------

    def _mint_corr(self) -> str:
        self._evac_seq += 1
        return f"evac-{self.seq}-{self._evac_seq}"

    def _on_replica_death(self, rep: Replica, reason: str, detail: str) -> None:
        """Immediate-evidence path (crash fault, engine wedge error): trip
        the breaker — counting to the threshold would route more traffic
        into the corpse — and evacuate now."""
        rep.evac_corr = rep.evac_corr or self._mint_corr()
        rep.last_verdict = reason
        rep.breaker.trip()
        self._set_state(rep, SUSPECT, reason, detail=detail)
        self._evacuate(rep, reason)

    def drain(self, name: str, reason: str = "scale_down") -> list[int]:
        """Planned evacuation (scale-down / rebalance): walk the same
        suspect → evacuating → drained lifecycle as a failure, under the
        same journal correlation, so operators read one vocabulary."""
        rep = self.replica(name)
        if rep.state == DRAINED:
            return []
        rep.evac_corr = rep.evac_corr or self._mint_corr()
        self._set_state(rep, SUSPECT, reason)
        return self._evacuate(rep, reason)

    def remove_replica(self, name: str):
        """Detach a DRAINED replica from the fleet and return its engine
        (the zero-loss pool-move building block: drain here, add_replica
        there).  Refuses any replica still in the lifecycle — removal
        must never strand resident streams."""
        rep = self.replica(name)
        if rep.state != DRAINED:
            raise ValueError(
                f"replica {name!r} is {rep.state}, not drained — "
                "drain() before remove_replica()"
            )
        self.replicas.remove(rep)
        for rid in [r for r, own in self._owner.items() if own is rep]:
            self._owner.pop(rid, None)
        if self.prefix_tier is not None:
            self.prefix_tier.on_replica_gone(rep.name, rep.engine)
        elif self.prefix_index is not None:
            self.prefix_index.invalidate_owner(rep.name)
        JOURNAL.record(
            "fleet", "replica.remove", correlation=name,
            engine=type(rep.engine).__name__,
        )
        self._publish_states()
        return rep.engine

    def _evacuate(self, rep: Replica, reason: str) -> list[int]:
        """snapshot → release → restore-onto-survivors.  Returns the ids
        moved (parked leftovers restore as capacity frees).  The whole
        operation journals under ONE correlation id."""
        corr = rep.evac_corr or self._mint_corr()
        rep.evac_corr = corr
        self._set_state(rep, EVACUATING, reason)
        try:
            snap = rep.engine.snapshot_active()
        except Exception as exc:
            # A replica too broken to snapshot loses its streams — record
            # loudly; the router still quarantines it out of the fleet.
            JOURNAL.record(
                "fleet", "evac.snapshot_failed", correlation=corr,
                replica=rep.name, error=f"{type(exc).__name__}: {exc}",
            )
            _M_EVAC.inc(reason="snapshot_failed")
            if self.prefix_tier is not None:
                self.prefix_tier.on_replica_gone(rep.name, rep.engine)
            elif self.prefix_index is not None:
                self.prefix_index.invalidate_owner(rep.name)
            self._set_state(rep, DRAINED, f"snapshot failed ({reason})")
            rep.evac_corr = ""
            return []
        entries = list(snap["requests"])
        JOURNAL.record(
            "fleet", "evac.snapshot", correlation=corr, replica=rep.name,
            requests=len(entries), engine=snap.get("engine", ""),
        )
        for req in entries:
            self._owner.pop(int(req["request_id"]), None)
        try:
            rep.engine.release_active()
        except Exception as exc:  # release is cleanup, never blocks the move
            JOURNAL.record(
                "fleet", "evac.release_failed", correlation=corr,
                replica=rep.name, error=f"{type(exc).__name__}: {exc}",
            )
        moved = self._place_entries(entries, corr, skip=rep)
        rep.evacuations += 1
        _M_EVAC.inc(reason=reason)
        # A drained replica's prefix blocks are unreachable: purge its
        # fleet-index entries (pinned ones die at unpin — an in-flight
        # pull is never raced) and stop publishing for it.
        if self.prefix_tier is not None:
            self.prefix_tier.on_replica_gone(rep.name, rep.engine)
        elif self.prefix_index is not None:
            self.prefix_index.invalidate_owner(rep.name)
        self._set_state(rep, DRAINED, reason)
        JOURNAL.record(
            "fleet", "evac.resumed", correlation=corr, replica=rep.name,
            moved=len(moved), parked=len(self._parked), reason=reason,
        )
        rep.evac_corr = ""
        return moved

    def _place_entries(self, entries: list, corr: str,
                       skip: Replica | None = None) -> list[int]:
        """Split evacuated entries across healthy replicas by free
        capacity and merge-restore each batch (bit-equal continuation —
        restore is the preemption-resume path).  Entries beyond fleet
        capacity park at the router and retry every tick."""
        moved: list[int] = []
        remaining = list(entries)
        for rep in self.replicas:
            if not remaining:
                break
            if rep is skip or rep.state != HEALTHY:
                continue
            if rep.breaker.state != CircuitBreaker.CLOSED:
                continue
            cap = rep.engine.free_slots()
            if cap <= 0:
                continue
            batch, remaining = remaining[:cap], remaining[cap:]
            try:
                restored = rep.engine.restore(
                    {"engine": "", "next_id": 0, "requests": batch}, merge=True
                )
            except RuntimeError as exc:
                # The replica refused the merge (e.g. it is itself draining
                # and its engine raised "needs an idle engine" under a
                # race): the entries are NOT lost — they go back to the
                # router's parking lot and retry on another replica next
                # tick.  Raising here would drop a whole evacuation batch.
                JOURNAL.record(
                    "fleet", "evac.restore_refused", correlation=corr,
                    replica=rep.name, requests=len(batch),
                    error=f"{type(exc).__name__}: {exc}",
                )
                rep.breaker.on_failure()
                remaining = batch + remaining
                continue
            JOURNAL.record(
                "fleet", "evac.restore", correlation=corr, replica=rep.name,
                requests=restored,
            )
            for rid in restored:
                self._owner[rid] = rep
            self._collect(rep)  # unrestorable entries deliver typed errors
            moved.extend(restored)
        for req in remaining:
            self._parked.append({"entry": req, "corr": corr})
        if remaining:
            JOURNAL.record(
                "fleet", "evac.parked", correlation=corr,
                requests=len(remaining),
            )
        return moved

    def _replay_parked(self) -> int:
        if not self._parked:
            return 0
        pending, self._parked = self._parked, []
        placed = 0
        for item in pending:
            moved = self._place_entries([item["entry"]], item["corr"])
            placed += len(moved)
        return placed

    # -- externally driven ticks (the disaggregated router's drive) -----------

    def tick(self) -> int:
        """ONE pump iteration without the front-door queue: health
        verdicts, parked-entry replay, one burst per live replica.
        Returns the number of slots stepped.  This is the drive surface
        :class:`~k8s_dra_driver_tpu.models.disagg.DisaggRouter` composes —
        it owns the cross-pool queue, this router owns its pool's health,
        placement and stepping."""
        self._tick += 1
        self._health_tick()
        self._replay_parked()
        for hook in self.tick_hooks:
            hook()
        return self._step_replicas()

    def place(self, entries: list, correlation: str = "") -> list[int]:
        """Public entry placement: merge-restore snapshot entries (e.g. a
        KV handoff batch) onto healthy replicas, parking what no replica
        can hold yet — exactly the evacuation placement path, so zero-loss
        parking and typed unrestorable errors come with it.  Returns the
        request ids placed now (parked entries place on later ticks)."""
        return self._place_entries(entries, correlation or f"place-{self.seq}")

    def idle(self) -> bool:
        """No queued, parked, resident or mid-admission work anywhere in
        this router's live replicas."""
        live = [r for r in self.replicas if r.state != DRAINED]
        return not self._parked and all(r.idle() for r in live)

    # -- state/observability ---------------------------------------------------

    def _set_state(self, rep: Replica, state: str, reason: str,
                   detail: str = "") -> None:
        prev, rep.state = rep.state, state
        JOURNAL.record(
            "fleet", f"replica.{state}",
            correlation=rep.evac_corr or rep.name,
            replica=rep.name, prev=prev, reason=reason,
            **({"detail": detail} if detail else {}),
        )
        self._publish_states()

    def _publish_states(self) -> None:
        counts = {s: 0 for s in STATES}
        for rep in self.replicas:
            counts[rep.state] += 1
        for state, n in counts.items():
            _M_REPLICAS.set(n, state=state)

    def _wedge(self, reason: str, queue: list) -> RuntimeError:
        from k8s_dra_driver_tpu.utils.watchdog import dump_diag_bundle

        state = self.stats()
        state["queue_depth"] = len(queue)
        JOURNAL.record(
            "fleet", "fleet.wedged", reason=reason, queue_depth=len(queue),
            parked=len(self._parked),
        )
        try:
            bundle = dump_diag_bundle(
                WATCHDOG.bundle_dir, reason=reason, state=state
            )
            detail = f" (diag bundle: {bundle})"
        except Exception as exc:
            detail = f" (diag bundle failed: {type(exc).__name__}: {exc})"
        return RuntimeError(reason + detail)

    def stats(self) -> dict:
        """The /debug/fleet contract: per-replica state (health lifecycle
        + breaker + the replica's last EngineStats) and the fleet queue."""
        return {
            "router_seq": self.seq,
            "tick": self._tick,
            "queue_depth": self._queue_depth,
            "parked": len(self._parked),
            "shed_count": self.shed_count,
            "replicas": [rep.to_json() for rep in self.replicas],
        }


_LIVE_ROUTERS: "weakref.WeakSet[FleetRouter]" = weakref.WeakSet()


def live_routers() -> list[FleetRouter]:
    return sorted(list(_LIVE_ROUTERS), key=lambda r: r.seq)


def debug_fleet_doc() -> dict:
    """The /debug/fleet payload: every live router's per-replica state and
    front-door queue depth (the fleet counterpart of /debug/serve)."""
    return {"fleets": [router.stats() for router in live_routers()]}
