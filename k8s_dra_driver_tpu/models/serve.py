"""Continuous-batching serving engine over the KV cache.

Sequential `greedy_decode` serves one batch at a time: every request in the
batch waits for the longest one, and new requests wait for the whole batch.
Real serving interleaves — this engine keeps a fixed pool of SLOTS (static
shapes: the cache is [L, n_slots, max_seq, H, hd] forever, so XLA compiles
exactly two programs — prefill-into-slot and step) and lets requests join
and leave per step:

* ``submit`` prefills a free slot with the prompt in ONE parallel forward
  (`decode.prefill`, padded to a bucket length to bound recompiles) and
  marks it active;
* ``step`` advances EVERY active slot by one token in a single fused
  program — per-slot positions, per-row cache scatter, inactive slots
  masked out;
* finished slots (eos or max_tokens) free immediately and the next submit
  reuses them.

Numerics contract (tested): a request served through the engine produces
EXACTLY the tokens sequential `greedy_decode` produces for the same prompt
— continuous batching changes scheduling, never results.  Caveat on the
"exactly": the engine admits via the PARALLEL prefill, whose k/v agree
with the sequential scan's to float tolerance, not necessarily bit-for-bit
(tests/test_decode.py pins the prefill-mode parity at atol 2e-5); on a
degenerate model whose argmax sits on a near-tie, that low-bit difference
can pick the other tied token.  Real checkpoints don't generate off
coin-flip logits; the bit-equality tests pin the shipped configs.

The reference has no serving story at all (its data plane is CUDA inside
user pods); this is consumer-side capability per SURVEY.md §2.11.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models import decode
from k8s_dra_driver_tpu.models.burnin import ModelConfig
from k8s_dra_driver_tpu.models.decode import KVCache, init_cache


def _step_all_slots(
    params, cache: KVCache, tokens, pos, active, temps, keys,
    *, cfg: ModelConfig, top_k: int,
):
    """One decode step for every slot at its OWN position: exactly
    :func:`decode.decode_step` with vector positions and the active gate —
    one step implementation for both decode paths, so the engine's
    bit-equality contract cannot drift.

    Per-slot sampling: ``temps`` [B] f32 (0 = greedy, the bit-equality
    case), ``keys`` [B, 2] per-request BASE keys — the step key derives
    statelessly as fold_in(base, pos), so replaying a request is
    deterministic without threading RNG state through the host loop;
    ``top_k`` is engine-wide (lax.top_k needs a static k).
    Returns (next_token [B], cache)."""
    logits, cache = decode.decode_step(
        params, cache, tokens, pos, cfg=cfg, active=active
    )
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if top_k > 0:
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    step_keys = jax.vmap(jax.random.fold_in)(keys, pos)
    sampled = jax.vmap(jax.random.categorical)(step_keys, scaled)
    tok = jnp.where(temps > 0.0, sampled, greedy)
    return tok.astype(jnp.int32), cache


def _commit_row_and_first_token(
    params, cache: KVCache, row_k, row_v, prompt, plen, slot, temp, key,
    *, cfg, top_k: int,
):
    """Shared admission tail for BOTH prefill paths (full and prefix-hit):
    zero the row's garbage tail (>= plen), scatter it into the slot, and
    compute the first generated token by re-running the per-slot step at
    pos = plen-1 — bit-identical to what sequential decode computes there
    (the k/v re-write at that position is idempotent: same token, same
    position).  ONE implementation so hit- and miss-path streams cannot
    drift.

    Causality makes padding safe: k/v at position j depend only on
    positions <= j, so every j < plen came from real tokens and the
    garbage tail is zeroed here and mask-excluded forever after."""
    keep = (jnp.arange(cache.k.shape[2]) < plen)[None, :, None, None]
    new_cache = KVCache(
        cache.k.at[:, slot].set(jnp.where(keep, row_k, 0).astype(cache.k.dtype)),
        cache.v.at[:, slot].set(jnp.where(keep, row_v, 0).astype(cache.v.dtype)),
    )
    last_tok = prompt[0, plen - 1]
    n_slots = cache.k.shape[1]
    tok, new_cache = _step_all_slots(
        params,
        new_cache,
        jnp.full((n_slots,), last_tok, jnp.int32),
        jnp.full((n_slots,), plen - 1, jnp.int32),
        jnp.arange(n_slots) == slot,
        jnp.full((n_slots,), temp, jnp.float32),
        jnp.broadcast_to(key, (n_slots, *key.shape)),
        cfg=cfg,
        top_k=top_k,
    )
    return tok[slot], new_cache


def _prefill_into_slot(
    params, cache: KVCache, prompt, plen, slot, temp, key, *, cfg, top_k: int
):
    """Fill ONE slot's cache from a padded prompt [1, bucket] in one
    parallel forward; returns (first generated token, new cache).  The
    padded prefill's OWN last-logits are at position bucket-1 (wrong for
    padded prompts) and are discarded; `_commit_row_and_first_token` owns
    the admission tail."""
    slot_cache, _ = decode.prefill(
        params, prompt, cfg, max_seq=cache.k.shape[2], cache_dtype=cache.k.dtype
    )
    return _commit_row_and_first_token(
        params, cache, slot_cache.k[:, 0], slot_cache.v[:, 0],
        prompt, plen, slot, temp, key, cfg=cfg, top_k=top_k,
    )


def _prefill_suffix_into_slot(
    params, cache: KVCache, prefix_k, prefix_v, prompt, plen, slot, temp, key,
    *, cfg, top_k: int, prefix_bucket: int,
):
    """Prefix-cache hit path: write the stored prefix k/v (positions
    ``< prefix_bucket``) and compute ONLY the suffix's k/v with one
    `decode_chunk` at ``pos0=prefix_bucket`` — the shared-system-prompt
    admission saving.

    Bit-equality with the full prefill holds by construction: (a) the
    stored prefix bytes came out of this engine's own full-prefill program,
    whose k/v at positions ``< prefix_bucket`` depend only on the prefix
    tokens (causality) — same program, same inputs, same bits; (b) the
    suffix chunk contracts attention over the same ``k_window`` (the
    prompt bucket) the full prefill uses, so its reductions match shape
    for shape.  Returns (first generated token, new cache)."""
    bucket = prompt.shape[1]
    max_seq = cache.k.shape[2]
    row = init_cache(cfg, 1, max_seq, dtype=cache.k.dtype)
    row = KVCache(
        row.k.at[:, 0, :prefix_bucket].set(prefix_k.astype(row.k.dtype)),
        row.v.at[:, 0, :prefix_bucket].set(prefix_v.astype(row.v.dtype)),
    )
    suffix = prompt[:, prefix_bucket:]
    _, row = decode.decode_chunk(
        params, row, suffix, prefix_bucket, cfg=cfg, k_window=bucket
    )
    return _commit_row_and_first_token(
        params, cache, row.k[:, 0], row.v[:, 0],
        prompt, plen, slot, temp, key, cfg=cfg, top_k=top_k,
    )


def _extract_prefix(cache: KVCache, slot, *, prefix_bucket: int):
    """The slot's k/v for positions < prefix_bucket (store entry)."""
    return cache.k[:, slot, :prefix_bucket], cache.v[:, slot, :prefix_bucket]


@dataclass
class _Slot:
    request_id: int
    tokens: list[int]  # prompt + generated so far
    prompt_len: int
    max_tokens: int


@dataclass
class Completion:
    request_id: int
    tokens: list[int]  # prompt + generated
    generated: list[int]


@dataclass
class ServeEngine:
    """Host-side scheduler around the two jitted programs.

    Per-request temperature (0 = greedy, the bit-equality case) with
    deterministic stateless RNG (step key = fold_in(request seed, pos));
    ``top_k`` is engine-wide because lax.top_k requires a static k.  Not
    thread-safe — drive it from one loop, like the kubelet drives the
    plugin.
    """

    params: dict
    cfg: ModelConfig
    n_slots: int = 8
    prompt_bucket: int = 64
    cache_dtype: object = jnp.float32
    eos_id: int | None = None
    top_k: int = 0
    # Data-parallel serving: shard the SLOT axis over a mesh axis — each
    # device owns n_slots/axis_size slots' cache and step compute.  Every
    # per-slot op is row-independent, so sharding the row axis preserves
    # numerics exactly (the engine's bit-equality contract extends to the
    # sharded engine; tested).  Weights are replicated (TP-sharded serving
    # composes at the params level, orthogonal to slot scheduling).
    mesh: object | None = None
    slot_axis: str = "data"
    # Prefix caching: with ``prefix_bucket`` set (< prompt_bucket), the k/v
    # of each distinct ``prompt[:prefix_bucket]`` is stored once (LRU over
    # ``prefix_cache_entries``); later prompts sharing it skip the prefix's
    # prefill compute — the shared-system-prompt serving optimization.
    # Token streams are bit-identical with caching on or off (tested).
    prefix_bucket: int | None = None
    prefix_cache_entries: int = 8

    _cache: KVCache = field(init=False)
    _last: jax.Array = field(init=False)
    _pos: jax.Array = field(init=False)
    _active: jax.Array = field(init=False)
    _slots: list = field(init=False)
    _next_id: int = field(init=False, default=0)
    _completions: list = field(init=False, default_factory=list)

    def __post_init__(self):
        cfg = self.cfg
        if self.prompt_bucket > cfg.max_seq:
            raise ValueError(
                f"prompt_bucket ({self.prompt_bucket}) exceeds max_seq ({cfg.max_seq})"
            )
        if not 0 <= self.top_k <= cfg.vocab_size:
            raise ValueError(
                f"top_k ({self.top_k}) must be in [0, vocab_size={cfg.vocab_size}]"
            )
        if self.mesh is None:
            self._cache = init_cache(
                cfg, self.n_slots, cfg.max_seq, dtype=self.cache_dtype
            )
            self._last = jnp.zeros((self.n_slots,), jnp.int32)
            self._pos = jnp.zeros((self.n_slots,), jnp.int32)
            self._active = jnp.zeros((self.n_slots,), bool)
            self._temps = jnp.zeros((self.n_slots,), jnp.float32)
            self._keys = jnp.stack([jax.random.PRNGKey(0)] * self.n_slots)
        else:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            if self.slot_axis not in self.mesh.shape:
                raise ValueError(
                    f"slot_axis {self.slot_axis!r} is not a mesh axis "
                    f"(mesh has {list(self.mesh.shape)})"
                )
            axis = self.mesh.shape[self.slot_axis]
            if self.n_slots % axis:
                raise ValueError(
                    f"n_slots ({self.n_slots}) must divide over "
                    f"{self.slot_axis!r} axis size {axis}"
                )

            def sharding(spec):
                return NamedSharding(self.mesh, spec)

            # State is CREATED sharded (jit with out_shardings): the full
            # unsharded cache never materializes on one device — at serving
            # scale that intermediate is the peak-memory point.
            slot_dim = P(self.slot_axis)
            cache_s = sharding(P(None, self.slot_axis))
            self._cache = jax.jit(
                lambda: init_cache(cfg, self.n_slots, cfg.max_seq, dtype=self.cache_dtype),
                out_shardings=KVCache(cache_s, cache_s),
            )()
            make = jax.jit(
                lambda: (
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), jnp.int32),
                    jnp.zeros((self.n_slots,), bool),
                    jnp.zeros((self.n_slots,), jnp.float32),
                    jnp.stack([jax.random.PRNGKey(0)] * self.n_slots),
                ),
                out_shardings=(
                    sharding(slot_dim), sharding(slot_dim), sharding(slot_dim),
                    sharding(slot_dim), sharding(P(self.slot_axis, None)),
                ),
            )
            self._last, self._pos, self._active, self._temps, self._keys = make()
            self.params = jax.device_put(self.params, sharding(P()))
        self._slots = [None] * self.n_slots
        self._step_fn = jax.jit(
            functools.partial(_step_all_slots, cfg=cfg, top_k=self.top_k)
        )
        self._prefill_fn = jax.jit(
            functools.partial(_prefill_into_slot, cfg=cfg, top_k=self.top_k)
        )
        from collections import OrderedDict

        self._prefix_store: OrderedDict = OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        self._suffix_fn = self._extract_fn = None  # fail fast when disabled
        if self.prefix_bucket is not None:
            if not 0 < self.prefix_bucket < self.prompt_bucket:
                raise ValueError(
                    f"prefix_bucket ({self.prefix_bucket}) must be in "
                    f"(0, prompt_bucket={self.prompt_bucket})"
                )
            if self.prefix_cache_entries < 1:
                raise ValueError("prefix_cache_entries must be >= 1")
            self._suffix_fn = jax.jit(
                functools.partial(
                    _prefill_suffix_into_slot, cfg=cfg, top_k=self.top_k,
                    prefix_bucket=self.prefix_bucket,
                )
            )
            self._extract_fn = jax.jit(
                functools.partial(_extract_prefix, prefix_bucket=self.prefix_bucket)
            )

    # -- public API --------------------------------------------------------
    def free_slots(self) -> int:
        return sum(1 for s in self._slots if s is None)

    def submit(
        self,
        prompt: list[int],
        max_tokens: int,
        temperature: float = 0.0,
        seed: int | None = None,
    ) -> int:
        """Prefill `prompt` into a free slot; returns a request id.
        Raises RuntimeError when no slot is free (callers queue upstream —
        admission control is theirs, scheduling is ours)."""
        if not prompt:
            raise ValueError("empty prompt")
        if max_tokens < 1:
            raise ValueError(f"max_tokens must be >= 1, got {max_tokens}")
        if len(prompt) > self.prompt_bucket:
            raise ValueError(f"prompt {len(prompt)} exceeds bucket {self.prompt_bucket}")
        if len(prompt) + max_tokens > self.cfg.max_seq:
            raise ValueError("prompt + max_tokens exceeds max_seq")
        try:
            slot = self._slots.index(None)
        except ValueError:
            raise RuntimeError("no free slot") from None
        padded = jnp.zeros((1, self.prompt_bucket), jnp.int32)
        padded = padded.at[0, : len(prompt)].set(jnp.asarray(prompt, jnp.int32))
        request_id = self._next_id
        base_key = jax.random.PRNGKey(request_id if seed is None else seed)
        prefix_key = (
            tuple(prompt[: self.prefix_bucket])
            if self.prefix_bucket is not None and len(prompt) > self.prefix_bucket
            else None
        )
        if prefix_key is not None and prefix_key in self._prefix_store:
            self._prefix_store.move_to_end(prefix_key)  # LRU touch
            pk, pv = self._prefix_store[prefix_key]
            self.prefix_hits += 1
            first_tok, self._cache = self._suffix_fn(
                self.params, self._cache, pk, pv, padded, len(prompt), slot,
                jnp.float32(temperature), base_key,
            )
        else:
            first_tok, self._cache = self._prefill_fn(
                self.params, self._cache, padded, len(prompt), slot,
                jnp.float32(temperature), base_key,
            )
            if prefix_key is not None:
                self.prefix_misses += 1
                self._prefix_store[prefix_key] = self._extract_fn(self._cache, slot)
                while len(self._prefix_store) > self.prefix_cache_entries:
                    self._prefix_store.popitem(last=False)
        self._next_id += 1
        self._slots[slot] = _Slot(
            request_id, list(prompt) + [int(first_tok)], len(prompt), max_tokens
        )
        self._last = self._last.at[slot].set(first_tok)
        self._pos = self._pos.at[slot].set(len(prompt))
        self._active = self._active.at[slot].set(True)
        self._temps = self._temps.at[slot].set(temperature)
        self._keys = self._keys.at[slot].set(base_key)
        self._retire(slot)  # max_tokens=1 or eos on the first token
        return request_id

    def step(self) -> int:
        """Advance every active slot one token; returns #active before the
        step.  Finished requests move to ``completions()``.

        One device->host transfer per step (the token vector): occupancy is
        host-side bookkeeping, and per-slot device reads would serialize
        the loop against the device once per slot per token."""
        n_active = self.n_slots - self.free_slots()
        if n_active == 0:
            return 0
        next_tok, self._cache = self._step_fn(
            self.params, self._cache, self._last, self._pos, self._active,
            self._temps, self._keys,
        )
        self._last = jnp.where(self._active, next_tok, self._last)
        self._pos = jnp.where(self._active, self._pos + 1, self._pos)
        toks = np.asarray(next_tok).tolist()
        for slot, st in enumerate(self._slots):
            if st is None:
                continue
            st.tokens.append(toks[slot])
            self._retire(slot)
        return n_active

    def run_until_drained(self, max_steps: int = 10_000) -> None:
        for _ in range(max_steps):
            if self.step() == 0:
                return
        raise RuntimeError("serving loop did not drain")

    def completions(self) -> list:
        out, self._completions = self._completions, []
        return out

    # -- internals ---------------------------------------------------------
    def _retire(self, slot: int) -> None:
        """Free the slot if its request just finished (eos or max_tokens;
        submit() guarantees prompt + max_tokens <= max_seq, so the cache
        can never run out of positions mid-stream)."""
        st = self._slots[slot]
        n_gen = len(st.tokens) - st.prompt_len
        assert len(st.tokens) <= self.cfg.max_seq, "cache overrun: submit() invariant broken"
        hit_eos = self.eos_id is not None and st.tokens[-1] == self.eos_id
        if n_gen >= st.max_tokens or hit_eos:
            self._completions.append(
                Completion(
                    request_id=st.request_id,
                    tokens=list(st.tokens),
                    generated=list(st.tokens[st.prompt_len :]),
                )
            )
            self._slots[slot] = None
            self._active = self._active.at[slot].set(False)
