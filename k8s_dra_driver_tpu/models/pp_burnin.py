"""Pipeline-parallel burn-in training (pipe × data × model mesh).

Depth-shards the burn-in transformer across the ``pipe`` axis using
ops/pipeline.py's GPipe ring, with manual Megatron tensor parallelism inside
the shard_map (column-sharded in-projections, row-sharded out-projections,
explicit ``psum`` over ``model``).  Embedding/unembedding stay outside the
shard_map under normal jit sharding.

Constraints (validated): layers % pipe == 0, per-data-shard batch % n_micro
== 0, seq axis unused (ring-attention SP composes with TP/DP, not with the
pipeline path — pick one per workload, like every production stack).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models.burnin import (
    ModelConfig,
    TrainStepFns,
    _rms_norm,
    init_params,
    make_optimizer,
    make_sgd_step,
    shift_nll,
)
from k8s_dra_driver_tpu.ops.pipeline import pipeline_apply, stack_blocks, stage_scan


def _groupmajor_qkv(w, cfg: ModelConfig):
    """[D, q(Hq)|k(Hkv)|v(Hkv) packed] -> [D, group-major (Hkv, G*hd q +
    hd k + hd v)] so TP column shards hold whole KV GROUPS — each shard's
    columns carry G query heads together with THEIR kv head, which is what
    lets GQA tensor-shard without widening or scrambling the narrow k/v.
    MHA (G=1) reduces to the head-major [q_h | k_h | v_h] layout."""
    d = cfg.d_model
    h, hkv, hd = cfg.n_heads, cfg.kv_heads, cfg.head_dim
    g = cfg.kv_groups
    wq = w[:, : h * hd].reshape(d, hkv, g * hd)
    wk = w[:, h * hd : (h + hkv) * hd].reshape(d, hkv, hd)
    wv = w[:, (h + hkv) * hd :].reshape(d, hkv, hd)
    return jnp.concatenate([wq, wk, wv], axis=2).reshape(d, (h + 2 * hkv) * hd)


def pp_params_from_dense(dense: dict, cfg: ModelConfig) -> dict:
    """Convert burnin's dense param tree to the pipeline layout (stacked
    blocks + group-major qkv).  RoPE configs carry no pos_embed — positions
    are rotated into q/k inside the stage scan."""
    if cfg.n_experts:
        # The stage scan's stacked-block specs model the DENSE MLP pair;
        # MoE training runs on the non-pipelined mesh path (burnin TP
        # shards expert FF dims) or ops/moe's EP dispatch.  Say so here,
        # not deep inside a stacked-tree mismatch.
        raise ValueError(
            "pipeline training does not support MoE blocks; use "
            "build_train_step (TP/DP/SP) or ops/moe.topk_moe (EP)"
        )
    blocks = [
        {**blk, "qkv": _groupmajor_qkv(blk["qkv"], cfg)} for blk in dense["blocks"]
    ]
    out = {
        "embed": dense["embed"],
        "ln_f": dense["ln_f"],
        "blocks": stack_blocks(blocks),
    }
    if not cfg.rope:
        out["pos_embed"] = dense["pos_embed"]
    return out

# Stacked-block param layout: leading dim = layer, sharded over `pipe`;
# Megatron TP layout on the trailing dims.
_STACKED_SPECS = {
    "ln1": P("pipe"),
    "qkv": P("pipe", None, "model"),
    "attn_out": P("pipe", "model", None),
    "ln2": P("pipe"),
    "mlp_up": P("pipe", None, "model"),
    "mlp_down": P("pipe", "model", None),
}


def _tp_attention_core(qkv, b: int, s: int, tp: int, cfg: ModelConfig, dtype):
    """Shared attention math for BOTH TP block variants: group-major qkv
    [b, s, (Hkv/tp)*(G+2)*hd] -> attention output [b, s, d/tp].  One
    implementation so the mask/f32-softmax/scaling policy cannot drift
    between tp modes.  GQA contracts each local KV head against its G
    query heads directly (the narrow k/v is never widened), and RoPE
    rotates q/k by absolute position right here — inside the stage scan —
    so pipeline stages need no position plumbing beyond the sequence
    length."""
    from k8s_dra_driver_tpu.models.burnin import rope_rotate

    hkv_loc = cfg.kv_heads // tp
    g, hd = cfg.kv_groups, cfg.head_dim
    qkv = qkv.reshape(b, s, hkv_loc, (g + 2) * hd)
    q = qkv[..., : g * hd].reshape(b, s, hkv_loc * g, hd)
    k = qkv[..., g * hd : (g + 1) * hd]  # [b, s, hkv_loc, hd]
    v = qkv[..., (g + 1) * hd :]
    if cfg.rope:
        pos = jnp.arange(s, dtype=jnp.int32)
        q = rope_rotate(q, pos, cfg)
        k = rope_rotate(k, pos, cfg)
    qg = q.reshape(b, s, hkv_loc, g, hd)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) / jnp.sqrt(hd).astype(dtype)
    mask = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
    weights = jax.nn.softmax(scores, axis=-1).astype(dtype)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return out.reshape(b, s, cfg.d_model // tp)


def _manual_tp_block_sp(x, p, cfg: ModelConfig, tp: int):
    """Megatron-SP variant of the TP block (Korthikanti et al.): the
    residual stream stays SEQUENCE-SHARDED over ``model`` between matmuls
    (activation memory / tp instead of full), the column-parallel
    projections gather it back with :func:`all_gather_matmul` (the gather
    rides under the chunk matmuls), and the row-parallel projections
    REDUCE-SCATTER instead of psum — half the collective bytes of classic
    Megatron, all of them overlapped.

    x: [b, s/tp, D] seq-sharded (vs the classic block's replicated [b,s,D]).
    """
    from k8s_dra_driver_tpu.ops.collective_matmul import (
        all_gather_matmul,
        matmul_reduce_scatter,
    )

    b, s_loc, _d = x.shape
    s = s_loc * tp

    gather_mm = jax.vmap(lambda y, w: all_gather_matmul(y, w, "model"), (0, None))
    scatter_mm = jax.vmap(lambda y, w: matmul_reduce_scatter(y, w, "model"), (0, None))

    y = _rms_norm(x, p["ln1"])  # per-token: valid on the seq shard
    qkv = gather_mm(y, p["qkv"])  # [b, s, h_loc*3*hd] — full sequence
    attn = _tp_attention_core(qkv, b, s, tp, cfg, x.dtype)
    x = x + scatter_mm(attn, p["attn_out"])  # [b, s/tp, D]

    y = _rms_norm(x, p["ln2"])
    h = jax.nn.gelu(gather_mm(y, p["mlp_up"]))
    x = x + scatter_mm(h, p["mlp_down"])
    return x


def _manual_tp_block(x, p, cfg: ModelConfig, tp: int):
    """One transformer block with weights TP-sliced over `model` (call inside
    shard_map; x is model-replicated [b, s, D])."""
    b, s, _d = x.shape

    y = _rms_norm(x, p["ln1"])
    # p["qkv"] is group-major (see _groupmajor_qkv): each TP shard's columns
    # are whole heads carrying their own q,k,v — a naive [q|k|v]-packed
    # column shard would split k across devices.
    qkv = jnp.einsum("bsd,de->bse", y, p["qkv"])  # [b, s, h_loc*3*hd]
    attn = _tp_attention_core(qkv, b, s, tp, cfg, x.dtype)
    # Row-parallel out-projection: partial sums reduced over `model`.
    x = x + jax.lax.psum(jnp.einsum("bse,ed->bsd", attn, p["attn_out"]), "model")

    y = _rms_norm(x, p["ln2"])
    y = jax.nn.gelu(jnp.einsum("bsd,df->bsf", y, p["mlp_up"]))
    x = x + jax.lax.psum(jnp.einsum("bsf,fd->bsd", y, p["mlp_down"]), "model")
    return x


def build_pp_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    lr: float = 3e-4,
    n_micro: int | None = None,
    tp_mode: str = "megatron",
) -> TrainStepFns:
    """``tp_mode``: 'megatron' (replicated activations, psum reductions) or
    'megatron-sp' (sequence-sharded residual stream with the overlapped
    collective-matmul rings from ops/collective_matmul.py — less activation
    memory, half the collective bytes, transfers hidden under compute)."""
    if tp_mode not in ("megatron", "megatron-sp"):
        raise ValueError(f"tp_mode must be 'megatron' or 'megatron-sp', got {tp_mode!r}")
    pp = mesh.shape.get("pipe", 1)
    tp = mesh.shape.get("model", 1)
    if pp < 2:
        raise ValueError("build_pp_train_step needs a mesh with pipe >= 2")
    if mesh.shape.get("seq", 1) != 1:
        raise ValueError("the pipeline path composes with data/model axes only")
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide into {pp} stages")
    if cfg.kv_heads % tp:
        # TP shards whole KV groups (each query head rides with its kv
        # head), so the KV head count is the sharding granularity.
        raise ValueError(
            f"n_kv_heads ({cfg.kv_heads}) not divisible by model axis {tp}"
        )
    if cfg.d_ff % tp or cfg.d_model % tp:
        raise ValueError(
            f"d_ff ({cfg.d_ff}) and d_model ({cfg.d_model}) must be divisible "
            f"by model axis {tp}"
        )
    n_micro = n_micro or pp
    opt = make_optimizer(lr)

    outer_specs = {
        "embed": P("model", None),
        "ln_f": P(),
    }
    if not cfg.rope:  # the table exists only without RoPE; specs must match
        outer_specs["pos_embed"] = P()
    param_shardings = {
        **{k: NamedSharding(mesh, s) for k, s in outer_specs.items()},
        "blocks": {k: NamedSharding(mesh, s) for k, s in _STACKED_SPECS.items()},
    }
    data_sharding = NamedSharding(mesh, P("data", None))

    # Same remat tradeoff as the dense path: recompute block activations in
    # backward instead of keeping every per-tick intermediate live.
    block = _manual_tp_block_sp if tp_mode == "megatron-sp" else _manual_tp_block
    block_fn = jax.checkpoint(functools.partial(block, cfg=cfg, tp=tp))
    stage_fn = functools.partial(stage_scan, block_fn)
    data_axis = mesh.shape.get("data", 1)

    # megatron-sp: the hand-off/residual stream is seq-sharded over `model`
    # inside the shard_map, so the microbatch spec carries S on that axis.
    seq_axis = "model" if tp_mode == "megatron-sp" else None
    mb_spec = P(None, "data", seq_axis, None)  # [n_micro, B, S, D]

    pipe_body = jax.shard_map(
        lambda blocks, x_mb: pipeline_apply(stage_fn, blocks, x_mb),
        mesh=mesh,
        in_specs=(_STACKED_SPECS, mb_spec),
        out_specs=mb_spec,
        check_vma=False,  # psum-replicated output; collection mask confuses vma
    )

    def forward(params, tokens):
        b, s = tokens.shape
        if b % n_micro or (b // n_micro) % data_axis:
            raise ValueError(
                f"batch {b} must split into {n_micro} microbatches each "
                f"divisible by the data axis ({data_axis})"
            )
        if tp_mode == "megatron-sp" and s % tp:
            raise ValueError(
                f"megatron-sp shards the sequence over the model axis: "
                f"seq {s} must be divisible by {tp}"
            )
        x = params["embed"][tokens]
        if not cfg.rope:
            x = x + params["pos_embed"][:s]
        x_mb = x.reshape(n_micro, b // n_micro, s, cfg.d_model)
        x = pipe_body(params["blocks"], x_mb).reshape(b, s, cfg.d_model)
        x = _rms_norm(x, params["ln_f"])
        return jnp.einsum("bsd,vd->bsv", x, params["embed"]).astype(jnp.float32)

    def loss_fn(params, tokens):
        return shift_nll(forward(params, tokens), tokens)

    def init(key):
        params = pp_params_from_dense(init_params(key, cfg), cfg)
        return params, opt.init(params)

    step = make_sgd_step(loss_fn, opt)

    jit_init = jax.jit(init, out_shardings=(param_shardings, None))
    jit_step = jax.jit(
        step,
        in_shardings=(param_shardings, None, data_sharding),
        out_shardings=(param_shardings, None, None),
        donate_argnums=(0, 1),
    )
    return TrainStepFns(init=jit_init, step=jit_step)
