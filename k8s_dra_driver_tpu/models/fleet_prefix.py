"""Fleet-wide KV prefix cache: distributed prefix index + cross-replica pull.

At fleet scale the same system prompts and conversation prefixes hit every
replica, but the prefix caches in `models/paged.py` (`_prefix_stores`) and
`models/serve.py` (dense `_prefix_store`) are strictly per-engine: a warm
cache on replica A does nothing for a request admitted on replica B.  This
module adds the fleet tier on top of them:

- `FleetPrefixIndex` — hash-of-token-prefix -> owning replica + KVSlice
  geometry (block_size, kv_dtype, adapter).  Engines publish as they store
  prefix blocks (via `on_prefix_store` / `on_prefix_evict` hooks) and the
  router consults it at admission.  TTL + capacity eviction with
  block-ledger accounting; pinned-while-pulling refcounts so eviction never
  races an in-flight pull; `invalidate_owner()` on scale-down/rebalance.
- `LocalPrefixSource` / `RemotePrefixSource` — the pull legs.  Local pulls
  (owner in the same process) still round-trip `KVSlice.to_wire()` /
  `from_wire()` so the exact wire-v2 validation (CRCs, quantized geometry)
  guards both paths.  Remote pulls ride the existing `models/transport.py`
  framed link: PREFIXREQ out, PREFIXKV / PREFIXMISS back, bounded by the
  link's breaker + heartbeat liveness.
- `FleetPrefixTier` — admission-time consumer bound to a `FleetRouter`.
  Routes-to-home wins when affinity is free (depth-aware scoring lives in
  `fleet._candidates`); otherwise `prepare()` pulls the prefix KV from the
  owner and injects it via the engine's cached-blocks path so the
  subsequent `submit()` takes the *existing* prefix-hit ladder — which is
  what makes remote-pull decode bit-equal to cold prefill.

Fallback ladder (cost, never correctness): geometry mismatch, breaker
open, PREFIXMISS, mid-pull owner death, or inject failure all land on cold
prefill; a dead owner is invalidated from the index on the way down.

Like `models/fleet.py`, this module stays importable without jax — the
engines bring jax; KVSlice is imported lazily at pull time.
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass, field

from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_M_PREFIX_HITS = REGISTRY.counter(
    "tpu_fleet_prefix_hits_total",
    "Admissions served from the fleet prefix-cache tier by source: "
    "local = the admitting replica already held the prefix blocks, "
    "remote = prefix KV was pulled from the owning replica over the "
    "transport wire and injected before prefill.",
)
_M_PREFIX_PULL = REGISTRY.histogram(
    "tpu_fleet_prefix_pull_seconds",
    "Wall seconds per cross-replica prefix-KV pull attempt, measured from "
    "the PREFIXREQ send to injected blocks (misses and failed pulls that "
    "fell back to cold prefill included).",
)
_M_PREFIX_EVICT = REGISTRY.counter(
    "tpu_fleet_prefix_evictions_total",
    "Fleet prefix-index entries dropped by reason: ttl (expired sweep), "
    "capacity (index LRU), owner_evicted (the owning engine LRU-dropped "
    "the blocks), invalidated (owner drained/removed/rebalanced away).",
)


def prefix_digest(material, adapter: int = 0) -> str:
    """Stable digest of prefix key material (a token tuple, or any
    deterministic hashable stand-in — the workload simulator uses block
    identity tuples).  The index stores digests, not token content, so a
    4096-entry index over 1k-token prefixes stays tens of KiB."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((int(adapter), tuple(material))).encode("utf-8"))
    return h.hexdigest()


@dataclass
class PrefixEntry:
    """One published prefix: deepest token depth `n_tokens` at `owner`,
    plus the KVSlice geometry a puller must match (or fall back)."""

    key: str
    owner: str
    n_tokens: int
    block_size: int
    kv_dtype: str
    n_layers: int = 0
    kv_heads: int = 0
    head_dim: int = 0
    adapter: int = 0
    blocks: int = 1  # ledger blocks this entry accounts for on the owner
    expires_at: float = 0.0
    pins: int = 0
    dead: bool = False  # owner invalidated while pinned; drop at unpin


@dataclass(frozen=True)
class PrefixLedger:
    """Balanced-accounting snapshot: published blocks per owner."""

    blocks: dict = field(default_factory=dict)
    entries: int = 0
    pinned: int = 0


class FleetPrefixIndex:
    """Fleet-scoped map: digest(adapter, token-prefix) -> PrefixEntry.

    Not thread-safe by design — it lives on the admission path of one
    router (same single-threaded discipline as `FleetRouter` itself).
    Entries are hints: the owner re-validates on PREFIXREQ, so a stale
    entry costs one miss round-trip, never correctness.
    """

    def __init__(
        self,
        *,
        ttl_s: float = 300.0,
        max_entries: int = 4096,
        clock=time.monotonic,
    ) -> None:
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._clock = clock
        self._entries: dict[str, PrefixEntry] = {}  # insertion order = LRU
        self._block_sizes: set[int] = set()
        self.published_total = 0
        self.evicted_total = 0

    # -- publish / withdraw -------------------------------------------------

    def publish(
        self,
        material,
        owner: str,
        *,
        n_tokens: int,
        block_size: int,
        kv_dtype: str,
        n_layers: int = 0,
        kv_heads: int = 0,
        head_dim: int = 0,
        adapter: int = 0,
        blocks: int = 1,
    ) -> PrefixEntry:
        key = prefix_digest(material, adapter)
        now = self._clock()
        ent = self._entries.get(key)
        if ent is not None and not ent.dead:
            # Refresh: newest publisher wins the owner slot (rebalance moves
            # blocks around); bump expiry and LRU position.
            ent.owner = owner
            ent.expires_at = now + self.ttl_s
            ent.kv_dtype = str(kv_dtype)
            ent.block_size = int(block_size)
            ent.blocks = int(blocks)
            self._entries[key] = self._entries.pop(key)
            return ent
        ent = PrefixEntry(
            key=key,
            owner=str(owner),
            n_tokens=int(n_tokens),
            block_size=int(block_size),
            kv_dtype=str(kv_dtype),
            n_layers=int(n_layers),
            kv_heads=int(kv_heads),
            head_dim=int(head_dim),
            adapter=int(adapter),
            blocks=int(blocks),
            expires_at=now + self.ttl_s,
        )
        self._entries[key] = ent
        self._block_sizes.add(int(block_size))
        self.published_total += 1
        self._evict_over_capacity()
        return ent

    def withdraw(self, material, adapter: int = 0, *, owner: str | None = None,
                 reason: str = "owner_evicted") -> bool:
        """The owning engine LRU-dropped these blocks (on_prefix_evict)."""
        key = prefix_digest(material, adapter)
        ent = self._entries.get(key)
        if ent is None or (owner is not None and ent.owner != owner):
            return False
        self._drop(ent, reason)
        return True

    def _drop(self, ent: PrefixEntry, reason: str) -> None:
        if ent.pins > 0:
            # Never race an in-flight pull: keep the entry until unpin.
            ent.dead = True
            return
        self._entries.pop(ent.key, None)
        self.evicted_total += 1
        _M_PREFIX_EVICT.inc(reason=reason)

    def _evict_over_capacity(self) -> None:
        while len(self._entries) > self.max_entries:
            victim = None
            for ent in self._entries.values():  # oldest first
                if ent.pins == 0:
                    victim = ent
                    break
            if victim is None:
                return  # everything pinned; capacity is advisory then
            self._drop(victim, "capacity")

    # -- lookup -------------------------------------------------------------

    def block_sizes(self):
        return sorted(self._block_sizes)

    def chain_for_tokens(self, tokens, adapter: int = 0):
        """Candidate chain [(n_tokens, material)] shallow->deep for a real
        token prompt, one rung per whole block at every granularity the
        fleet has published (paged block sizes and dense buckets alike)."""
        n = len(tokens)
        depths: set[int] = set()
        for bs in self._block_sizes:
            if bs <= 0:
                continue
            # A usable prefix must leave >= 1 token to prefill from.
            d = bs
            while d < n:
                depths.add(d)
                d += bs
        return [(d, tuple(tokens[:d])) for d in sorted(depths)]

    def _live(self, ent: PrefixEntry | None, now: float) -> PrefixEntry | None:
        if ent is None or ent.dead:
            return None
        if ent.expires_at <= now:
            self._drop(ent, "ttl")
            return None
        return ent

    def deepest(self, chain, adapter: int = 0, *, compatible=None):
        """Deepest live entry along the chain that passes `compatible(ent)`.
        Chain rungs are independent candidates (contiguity is the owner's
        problem — it re-walks its own store on PREFIXREQ)."""
        now = self._clock()
        for n_tokens, material in reversed(list(chain)):
            ent = self._live(self._entries.get(prefix_digest(material, adapter)), now)
            if ent is None or ent.n_tokens != n_tokens:
                continue
            if compatible is not None and not compatible(ent):
                continue
            return ent
        return None

    def survey(self, chain, adapter: int = 0) -> dict:
        """Per-owner deepest published depth along the chain, as
        {owner: (n_tokens, blocks)} — the router's depth-aware affinity
        signal."""
        now = self._clock()
        out: dict[str, tuple[int, int]] = {}
        for n_tokens, material in chain:
            ent = self._live(self._entries.get(prefix_digest(material, adapter)), now)
            if ent is None:
                continue
            best = out.get(ent.owner)
            if best is None or n_tokens > best[0]:
                depth_blocks = (
                    n_tokens // ent.block_size if ent.block_size > 0 else 1
                )
                out[ent.owner] = (n_tokens, max(1, depth_blocks))
        return out

    # -- pin / sweep / invalidate ------------------------------------------

    def pin(self, key: str) -> bool:
        ent = self._entries.get(key)
        if ent is None or ent.dead:
            return False
        ent.pins += 1
        return True

    def unpin(self, key: str) -> None:
        ent = self._entries.get(key)
        if ent is None:
            return
        ent.pins = max(0, ent.pins - 1)
        if ent.dead and ent.pins == 0:
            self._entries.pop(ent.key, None)
            self.evicted_total += 1
            _M_PREFIX_EVICT.inc(reason="invalidated")

    def sweep(self, now: float | None = None) -> int:
        """TTL sweep; returns entries dropped.  Pinned entries survive."""
        now = self._clock() if now is None else now
        expired = [e for e in self._entries.values() if e.expires_at <= now]
        dropped = 0
        for ent in expired:
            before = len(self._entries)
            self._drop(ent, "ttl")
            dropped += before - len(self._entries)
        return dropped

    def invalidate_owner(self, owner: str, *, reason: str = "invalidated") -> int:
        """Owner drained / removed / rebalanced: its entries are garbage.
        Unpinned entries drop now; pinned ones are marked dead and drop at
        unpin (never under an in-flight pull)."""
        victims = [e for e in self._entries.values() if e.owner == owner]
        dropped = 0
        for ent in victims:
            before = len(self._entries)
            self._drop(ent, reason)
            dropped += before - len(self._entries)
        if victims:
            JOURNAL.record(
                "fleet", "prefix.invalidate",
                owner=owner,
                entries=len(victims),
                dropped=dropped,
                reason=reason,
            )
        return dropped

    # -- accounting ---------------------------------------------------------

    def ledger(self) -> PrefixLedger:
        blocks: dict[str, int] = {}
        pinned = 0
        for ent in self._entries.values():
            blocks[ent.owner] = blocks.get(ent.owner, 0) + max(1, ent.blocks)
            if ent.pins > 0:
                pinned += 1
        return PrefixLedger(blocks=blocks, entries=len(self._entries), pinned=pinned)

    def __len__(self) -> int:
        return len(self._entries)

    def note_hit(self, source: str) -> None:
        _M_PREFIX_HITS.inc(source=source)


class LocalPrefixSource:
    """Pull leg for an owner replica in the same process.  Still round-trips
    the wire encoding so CRC + quantized-geometry validation is identical to
    the socket path (a corrupt export surfaces as WireFormatError -> cold
    prefill, exactly like a corrupt frame would)."""

    def __init__(self, name: str, engine) -> None:
        self.name = name
        self.engine = engine

    def pull(self, tokens, *, max_tokens=None, adapter: int = 0, nonce: int = 0):
        export = getattr(self.engine, "export_prefix_kv", None)
        if export is None:
            return None
        kv = export(tokens, max_tokens=max_tokens, adapter=adapter)
        if kv is None:
            return None
        from k8s_dra_driver_tpu.models.serve import KVSlice, WireFormatError

        try:
            _, out = KVSlice.from_wire(kv.to_wire(nonce))
        except WireFormatError:
            return None
        return out


class RemotePrefixSource:
    """Pull leg over a transport `PeerLink`: PREFIXREQ out, PREFIXKV or
    PREFIXMISS back, bounded by the link's breaker, heartbeat liveness, and
    a pull deadline.  Every failure mode returns None (cold prefill)."""

    def __init__(self, name: str, link, *, peer_pump=None,
                 pull_timeout_s: float = 5.0, clock=time.monotonic) -> None:
        self.name = name
        self.link = link
        self.peer_pump = peer_pump
        self.pull_timeout_s = float(pull_timeout_s)
        self._clock = clock

    def pull(self, tokens, *, max_tokens=None, adapter: int = 0, nonce: int = 0):
        import struct

        from k8s_dra_driver_tpu.models import transport as T
        from k8s_dra_driver_tpu.models.serve import KVSlice, WireFormatError

        decode_errors = (WireFormatError, struct.error, ValueError,
                         KeyError, UnicodeDecodeError)

        link = self.link
        if link.dead or not link.breaker.allow():
            return None
        try:
            link.send_json(
                T.PREFIXREQ,
                {
                    "nonce": int(nonce),
                    "tokens": [int(t) for t in tokens],
                    "max_tokens": None if max_tokens is None else int(max_tokens),
                    "adapter": int(adapter),
                },
            )
        except (T.TransportDownError, T.PeerDiedError, OSError):
            return None
        deadline = self._clock() + self.pull_timeout_s
        while True:
            try:
                link.pump()
                if self.peer_pump is not None and not link.dead:
                    self.peer_pump()
            except (T.TransportDownError, T.PeerDiedError, OSError):
                return None
            body = link.take(T.PREFIXKV)
            if body is not None:
                try:
                    meta, wire = T.decode_meta_frame(body)
                    if int(meta.get("nonce", -1)) != int(nonce):
                        continue  # stale reply from a timed-out earlier pull
                    rid, kv = KVSlice.from_wire(wire)
                except decode_errors:
                    return None
                if rid != int(nonce):
                    continue
                return kv
            body = link.take(T.PREFIXMISS)
            if body is not None:
                try:
                    meta, _ = T.decode_meta_frame(body)
                except decode_errors:
                    return None
                if int(meta.get("nonce", -1)) == int(nonce):
                    return None
                continue
            if link.dead or self._clock() >= deadline:
                return None
            if self.peer_pump is None:
                # Not a retry loop: the except arm above RETURNS (cold-
                # prefill fallback) — this is the deadline-bounded socket
                # poll, same cadence as transport.py's recv waits.
                time.sleep(0.002)  # lint: ignore[sleep-retry]

    @property
    def dead(self) -> bool:
        return bool(self.link.dead)


class FleetPrefixTier:
    """Admission-time consumer bound to one `FleetRouter` (via
    `router.attach_prefix_tier`).  `prepare()` runs just before
    `engine.submit()`: it classifies the admission as a local hit, pulls
    remote prefix KV into the engine's cached-blocks path, or leaves the
    request to cold prefill.  Any exception inside prepare is contained —
    the tier can only ever cost, never fail, an admission."""

    def __init__(
        self,
        index: FleetPrefixIndex | None = None,
        *,
        clock=time.monotonic,
        pull_timeout_s: float = 5.0,
        min_remote_tokens: int = 1,
    ) -> None:
        self.index = index if index is not None else FleetPrefixIndex(clock=clock)
        self._clock = clock
        self.pull_timeout_s = float(pull_timeout_s)
        self.min_remote_tokens = int(min_remote_tokens)
        self._sources: dict[str, object] = {}
        self._nonce = 0
        self.counts = {"local": 0, "remote": 0, "cold": 0}
        self.fallbacks: dict[str, int] = {}

    # -- wiring -------------------------------------------------------------

    def add_source(self, name: str, source) -> None:
        self._sources[name] = source

    def remove_source(self, name: str) -> None:
        self._sources.pop(name, None)

    def bind_engine(self, name: str, engine) -> None:
        """Attach publish/evict hooks so the engine feeds the index as it
        stores prefix blocks, and register a local pull source for it."""
        geom_fn = getattr(engine, "prefix_geometry", None)
        if geom_fn is None:
            return
        geom = dict(geom_fn())
        index = self.index

        def _on_store(material, n_tokens, adapter=0):
            index.publish(
                material,
                name,
                n_tokens=int(n_tokens),
                block_size=int(geom.get("block_size", 0)),
                kv_dtype=str(geom.get("kv_dtype", "")),
                n_layers=int(geom.get("n_layers", 0)),
                kv_heads=int(geom.get("kv_heads", 0)),
                head_dim=int(geom.get("head_dim", 0)),
                adapter=int(adapter),
                blocks=1,  # one store block per published depth rung
            )

        def _on_evict(material, adapter=0):
            index.withdraw(material, adapter, owner=name)

        engine.on_prefix_store = _on_store
        engine.on_prefix_evict = _on_evict
        if getattr(engine, "export_prefix_kv", None) is not None:
            self.add_source(name, LocalPrefixSource(name, engine))

    def unbind_engine(self, name: str, engine=None) -> None:
        if engine is not None:
            if getattr(engine, "on_prefix_store", None) is not None:
                engine.on_prefix_store = None
            if getattr(engine, "on_prefix_evict", None) is not None:
                engine.on_prefix_evict = None
        self.remove_source(name)

    def on_replica_gone(self, name: str, engine=None) -> None:
        """Scale-down / rebalance / death: invalidate everything it owned."""
        self.unbind_engine(name, engine)
        self.index.invalidate_owner(name)

    def tick(self) -> None:
        """Router tick hook: TTL sweep (pure dict work, no device syncs)."""
        self.index.sweep()

    # -- admission ----------------------------------------------------------

    def _note_fallback(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def _compatible(self, geom: dict, rep_name: str, local_depth: int):
        quantized_dtypes = ("int8", "int4")

        def check(ent: PrefixEntry) -> bool:
            if ent.owner == rep_name:
                return False
            if ent.n_tokens <= max(local_depth, self.min_remote_tokens - 1):
                return False
            if geom.get("n_layers") and ent.n_layers and ent.n_layers != geom["n_layers"]:
                return False
            if geom.get("kv_heads") and ent.kv_heads and ent.kv_heads != geom["kv_heads"]:
                return False
            if geom.get("head_dim") and ent.head_dim and ent.head_dim != geom["head_dim"]:
                return False
            # Bit-equality rule: pool dtypes must match exactly (cross-dtype
            # conversion is not bit-stable); quantized pools additionally
            # require the same block granularity because scales are
            # per-block.  Float payloads may re-block: the receiver installs
            # whole receiver-blocks, so it needs at least one.
            if ent.kv_dtype != geom.get("kv_dtype"):
                return False
            if ent.kv_dtype in quantized_dtypes:
                if ent.block_size != geom.get("block_size"):
                    return False
            else:
                bs = int(geom.get("block_size", 0) or 0)
                if bs > 0 and ent.n_tokens // bs < 1:
                    return False
            return True

        return check

    def prepare(self, rep_name: str, engine, prompt, *, max_tokens=None,
                adapter: int = 0, chain=None) -> str:
        """Classify + warm one admission.  Returns 'local' | 'remote' |
        'cold'.  Never raises past itself."""
        try:
            return self._prepare(rep_name, engine, prompt,
                                 max_tokens=max_tokens, adapter=adapter,
                                 chain=chain)
        except Exception as exc:  # containment: tier failures cost, not fail
            JOURNAL.record("fleet", "prefix.prepare_error", replica=rep_name,
                           error=f"{type(exc).__name__}: {exc}")
            self._note_fallback("error")
            self.counts["cold"] += 1
            return "cold"

    def _prepare(self, rep_name, engine, prompt, *, max_tokens, adapter, chain):
        depth_fn = getattr(engine, "local_prefix_depth", None)
        geom_fn = getattr(engine, "prefix_geometry", None)
        inject = getattr(engine, "inject_prefix_kv", None)
        local_depth = int(depth_fn(prompt, adapter)) if depth_fn is not None else 0
        if geom_fn is None or inject is None:
            if local_depth > 0:
                self.index.note_hit("local")
                self.counts["local"] += 1
                return "local"
            self.counts["cold"] += 1
            return "cold"
        geom = dict(geom_fn())
        if chain is None:
            chain = self.index.chain_for_tokens(prompt, adapter)
        ent = self.index.deepest(
            chain, adapter,
            compatible=self._compatible(geom, rep_name, local_depth))
        if ent is None:
            if local_depth > 0:
                self.index.note_hit("local")
                self.counts["local"] += 1
                return "local"
            self.counts["cold"] += 1
            return "cold"
        source = self._sources.get(ent.owner)
        if source is None:
            self._note_fallback("no_source")
            return self._after_failed_pull(local_depth)
        self._nonce += 1
        nonce = self._nonce
        pinned = self.index.pin(ent.key)
        t0 = self._clock()
        injected = 0
        try:
            kv = source.pull(prompt, max_tokens=max_tokens, adapter=adapter,
                             nonce=nonce)
            if kv is None:
                if getattr(source, "dead", False):
                    # Owner died mid-pull: its whole index footprint is
                    # garbage now, not just this entry.
                    self.on_replica_gone(ent.owner)
                    self._note_fallback("owner_dead")
                else:
                    self._note_fallback("miss")
                return self._after_failed_pull(local_depth)
            injected = int(inject(prompt, kv, adapter=adapter) or 0)
        finally:
            if pinned:
                self.index.unpin(ent.key)
            _M_PREFIX_PULL.observe(max(0.0, self._clock() - t0))
        if injected <= 0:
            self._note_fallback("inject")
            return self._after_failed_pull(local_depth)
        self.index.note_hit("remote")
        self.counts["remote"] += 1
        return "remote"

    def _after_failed_pull(self, local_depth: int) -> str:
        if local_depth > 0:
            self.index.note_hit("local")
            self.counts["local"] += 1
            return "local"
        self.counts["cold"] += 1
        return "cold"
