"""Fleet-wide KV prefix cache: distributed prefix index + cross-replica pull.

At fleet scale the same system prompts and conversation prefixes hit every
replica, but the prefix caches in `models/paged.py` (`_prefix_stores`) and
`models/serve.py` (dense `_prefix_store`) are strictly per-engine: a warm
cache on replica A does nothing for a request admitted on replica B.  This
module adds the fleet tier on top of them:

- `FleetPrefixIndex` — hash-of-token-prefix -> owning replica + KVSlice
  geometry (block_size, kv_dtype, adapter).  Engines publish as they store
  prefix blocks (via `on_prefix_store` / `on_prefix_evict` hooks) and the
  router consults it at admission.  TTL + capacity eviction with
  block-ledger accounting; pinned-while-pulling refcounts so eviction never
  races an in-flight pull; `invalidate_owner()` on scale-down/rebalance.
- `LocalPrefixSource` / `RemotePrefixSource` — the pull legs.  Local pulls
  (owner in the same process) still round-trip `KVSlice.to_wire()` /
  `from_wire()` so the exact wire-v2 validation (CRCs, quantized geometry)
  guards both paths.  Remote pulls ride the existing `models/transport.py`
  framed link: PREFIXREQ out, PREFIXKV / PREFIXMISS back, bounded by the
  link's breaker + heartbeat liveness.
- `FleetPrefixTier` — admission-time consumer bound to a `FleetRouter`.
  Routes-to-home wins when affinity is free (depth-aware scoring lives in
  `fleet._candidates`); otherwise `prepare()` pulls the prefix KV from the
  owner and injects it via the engine's cached-blocks path so the
  subsequent `submit()` takes the *existing* prefix-hit ladder — which is
  what makes remote-pull decode bit-equal to cold prefill.

Fallback ladder (cost, never correctness): geometry mismatch, breaker
open, PREFIXMISS, mid-pull owner death, or inject failure all land on cold
prefill; a dead owner is invalidated from the index on the way down.

Like `models/fleet.py`, this module stays importable without jax — the
engines bring jax; KVSlice is imported lazily at pull time.
"""

from __future__ import annotations

import hashlib
import json
import struct
import time
import zlib
from dataclasses import dataclass, field

from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_M_PREFIX_HITS = REGISTRY.counter(
    "tpu_fleet_prefix_hits_total",
    "Admissions served from the fleet prefix-cache tier by source: "
    "local = the admitting replica already held the prefix blocks, "
    "remote = prefix KV was pulled from the owning replica over the "
    "transport wire and injected before prefill.",
)
_M_PREFIX_PULL = REGISTRY.histogram(
    "tpu_fleet_prefix_pull_seconds",
    "Wall seconds per cross-replica prefix-KV pull attempt, measured from "
    "the PREFIXREQ send to injected blocks (misses and failed pulls that "
    "fell back to cold prefill included).",
)
_M_PREFIX_EVICT = REGISTRY.counter(
    "tpu_fleet_prefix_evictions_total",
    "Fleet prefix-index entries dropped by reason: ttl (expired sweep), "
    "capacity (index LRU), owner_evicted (the owning engine LRU-dropped "
    "the blocks), invalidated (owner drained/removed/rebalanced away), "
    "anti_entropy (reconnect digest showed the owner no longer holds it), "
    "epoch_fence (published under a superseded owner epoch).",
)
_M_PREFIX_PUB = REGISTRY.counter(
    "tpu_fleet_prefix_pub_total",
    "Prefix gossip events by outcome: shipped (owner worker put a "
    "PREFIXPUB/PREFIXWDL batch on the wire), shed (publish deferred to "
    "the next cadence tick by the byte budget), ingested (supervisor "
    "applied a publish), withdrawn (supervisor applied a withdraw), "
    "fenced (event carried a superseded owner epoch and was dropped), "
    "decode_drop (CRC/JSON-corrupt gossip frame dropped whole).",
)
_M_EPOCH_FENCES = REGISTRY.counter(
    "tpu_fleet_prefix_epoch_fences_total",
    "Stale-epoch fences on the fleet prefix tier: index entries dropped "
    "or gossip/pull answers rejected because they carried an owner epoch "
    "older than the current one (a restarted or replaced owner's stale "
    "state is a typed miss, never wrong KV).",
)
_M_PULL_ADMISSION = REGISTRY.counter(
    "tpu_fleet_prefix_pull_admission_total",
    "Ledger-gated remote prefix-pull admissions by outcome: admitted "
    "(blocks reserved against the KV-demand ledger for the transfer "
    "window), refused (over-demand — the pull falls back to cold "
    "prefill instead of competing with stream admission), bypass (no "
    "pull gate attached or decode headroom unaccountable).",
)


def prefix_digest(material, adapter: int = 0) -> str:
    """Stable digest of prefix key material (a token tuple, or any
    deterministic hashable stand-in — the workload simulator uses block
    identity tuples).  The index stores digests, not token content, so a
    4096-entry index over 1k-token prefixes stays tens of KiB."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((int(adapter), tuple(material))).encode("utf-8"))
    return h.hexdigest()


# -- gossip wire codec (PREFIXPUB / PREFIXWDL frame bodies) ------------------
#
# Owner workers batch publish/withdraw events and ship them to the
# supervisor's index as CRC'd frames on the worker pump cadence — the
# TELEM pattern, but with the owner epoch and a per-worker batch seq in
# a fixed header so a corrupt frame is attributable before it is trusted.
#
#   u32 crc32(epoch .. payload) | u32 epoch | u32 seq | json payload

GOSSIP_BUDGET_BYTES = 48 * 1024  # same per-frame ceiling as TELEM
_GOSSIP_CRC = struct.Struct("!I")
_GOSSIP_META = struct.Struct("!II")  # epoch, seq
_GOSSIP_HEADER_BYTES = _GOSSIP_CRC.size + _GOSSIP_META.size


class PrefixGossipError(ValueError):
    """Typed decode failure for a PREFIXPUB/PREFIXWDL body.  Carries the
    claimed owner ``epoch`` and batch ``seq`` (the gossip rid) once the
    fixed header is readable; -1 before — same attribution contract as
    ``WireFormatError.request_id``."""

    def __init__(self, message: str, *, epoch: int = -1, seq: int = -1):
        super().__init__(message)
        self.epoch = int(epoch)
        self.seq = int(seq)


def encode_prefix_gossip(doc: dict, *, epoch: int, seq: int) -> bytes:
    meta = _GOSSIP_META.pack(int(epoch) & 0xFFFFFFFF, int(seq) & 0xFFFFFFFF)
    payload = json.dumps(doc, separators=(",", ":"), sort_keys=True).encode("utf-8")
    crc = zlib.crc32(meta + payload) & 0xFFFFFFFF
    return _GOSSIP_CRC.pack(crc) + meta + payload


def decode_prefix_gossip(body: bytes) -> tuple[dict, int, int]:
    """Decode one gossip frame body -> (doc, epoch, seq).  EVERY
    truncation and EVERY bit flip is a ``PrefixGossipError`` — a corrupt
    batch is dropped whole, never partially applied to the index."""
    if len(body) < _GOSSIP_HEADER_BYTES:
        raise PrefixGossipError(
            f"gossip frame truncated at {len(body)} bytes "
            f"(< {_GOSSIP_HEADER_BYTES}-byte header)"
        )
    (crc,) = _GOSSIP_CRC.unpack_from(body)
    epoch, seq = _GOSSIP_META.unpack_from(body, _GOSSIP_CRC.size)
    if zlib.crc32(body[_GOSSIP_CRC.size:]) & 0xFFFFFFFF != crc:
        raise PrefixGossipError("gossip crc mismatch", epoch=epoch, seq=seq)
    try:
        doc = json.loads(body[_GOSSIP_HEADER_BYTES:].decode("utf-8"))
    except (UnicodeDecodeError, ValueError) as exc:
        raise PrefixGossipError(
            f"gossip payload undecodable: {exc}", epoch=epoch, seq=seq
        ) from exc
    if not isinstance(doc, dict):
        raise PrefixGossipError(
            "gossip payload is not an object", epoch=epoch, seq=seq
        )
    return doc, int(epoch), int(seq)


class PrefixGossip:
    """Worker-side gossip publisher: buffers ``on_prefix_store`` /
    ``on_prefix_evict`` events from the worker's engines and ships them
    as CRC'd PREFIXPUB / PREFIXWDL batches piggybacked on the worker pump
    cadence (the ``TelemetryShipper`` discipline: cadence-paced, byte-
    budgeted, no thread of its own, pure host-side dict work).

    Withdrawals always ship (a missed withdraw is a stale hint that costs
    a PREFIXMISS round-trip); publishes are priority-shed deepest-first
    under the byte budget, with shed events requeued for the next tick —
    delayed, never lost.  ``resync(epoch)`` arms a full-digest ship (the
    anti-entropy summary the supervisor reconciles against after a
    reconnect) and adopts the supervisor-assigned owner epoch."""

    def __init__(self, send, *, clock=time.monotonic, interval_s: float = 0.25,
                 budget_bytes: int = GOSSIP_BUDGET_BYTES) -> None:
        self.send = send  # callable(kind: "pub"|"wdl", body: bytes)
        self._clock = clock
        self.interval_s = float(interval_s)
        self.budget_bytes = int(budget_bytes)
        self.epoch = 0
        self.seq = 0
        self._held: dict[str, dict] = {}  # key -> event, everything we hold
        self._pub_q: dict[str, dict] = {}  # pending publishes (key-deduped)
        self._wdl_q: dict[str, dict] = {}  # pending withdraws
        self._full_pending = False
        self._last_ship = float("-inf")
        self.shipped_frames = 0
        self.shed_total = 0
        self.max_frame_bytes = 0

    def bind_engine(self, engine) -> None:
        geom_fn = getattr(engine, "prefix_geometry", None)
        if geom_fn is None:
            return
        geom = dict(geom_fn())

        def _on_store(material, n_tokens, adapter=0):
            self.note_store(material, n_tokens, adapter, geom)

        def _on_evict(material, adapter=0):
            self.note_evict(material, adapter)

        engine.on_prefix_store = _on_store
        engine.on_prefix_evict = _on_evict

    def note_store(self, material, n_tokens, adapter, geom: dict) -> None:
        key = prefix_digest(material, adapter)
        ev = {
            "key": key,
            "n_tokens": int(n_tokens),
            "block_size": int(geom.get("block_size", 0)),
            "kv_dtype": str(geom.get("kv_dtype", "")),
            "n_layers": int(geom.get("n_layers", 0)),
            "kv_heads": int(geom.get("kv_heads", 0)),
            "head_dim": int(geom.get("head_dim", 0)),
            "adapter": int(adapter),
            "blocks": 1,
        }
        self._held[key] = ev
        self._pub_q[key] = ev
        self._wdl_q.pop(key, None)

    def note_evict(self, material, adapter=0) -> None:
        key = prefix_digest(material, adapter)
        self._held.pop(key, None)
        self._pub_q.pop(key, None)
        self._wdl_q[key] = {"key": key, "adapter": int(adapter)}

    def resync(self, epoch: int) -> None:
        self.epoch = int(epoch)
        self._full_pending = True
        self._pub_q.clear()
        self._wdl_q.clear()

    def pending(self) -> bool:
        return bool(self._full_pending or self._pub_q or self._wdl_q)

    def maybe_ship(self, force: bool = False) -> int:
        """Ship pending batches if the cadence (or ``force``) says so.
        Returns frames shipped.  Never raises past itself — gossip is a
        hint plane; a send failure surfaces on the link, not here."""
        now = self._clock()
        if not force and now - self._last_ship < self.interval_s:
            return 0
        if not self.pending():
            return 0
        self._last_ship = now
        frames = 0
        if self._wdl_q:
            doc = {"events": list(self._wdl_q.values())}
            self._wdl_q.clear()
            frames += self._ship("wdl", doc)
        full = self._full_pending
        if full:
            events = sorted(
                self._held.values(), key=lambda e: -int(e.get("n_tokens", 0))
            )
            self._full_pending = False
            self._pub_q.clear()
        else:
            events = sorted(
                self._pub_q.values(), key=lambda e: -int(e.get("n_tokens", 0))
            )
            self._pub_q.clear()
        if events or full:
            kept = self._fit(events, full)
            doc = {"events": kept}
            if full:
                doc["full"] = True
            frames += self._ship("pub", doc)
        return frames

    def _fit(self, events: list, full: bool) -> list:
        """Priority shedding under the byte budget: deepest rungs ship
        first, the shallow tail is requeued for the next cadence tick."""
        used = _GOSSIP_HEADER_BYTES + len(
            json.dumps({"events": [], "full": full},
                       separators=(",", ":"), sort_keys=True)
        )
        kept: list = []
        for ev in events:
            ev_len = 1 + len(json.dumps(ev, separators=(",", ":"),
                                        sort_keys=True))
            if used + ev_len > self.budget_bytes:
                self._pub_q.setdefault(ev["key"], ev)
                self.shed_total += 1
                _M_PREFIX_PUB.inc(outcome="shed")
                continue
            kept.append(ev)
            used += ev_len
        return kept

    def _ship(self, kind: str, doc: dict) -> int:
        self.seq += 1
        body = encode_prefix_gossip(doc, epoch=self.epoch, seq=self.seq)
        try:
            self.send(kind, body)
        except Exception:  # noqa: BLE001 - link failures surface on the link
            return 0
        self.shipped_frames += 1
        self.max_frame_bytes = max(self.max_frame_bytes, len(body))
        _M_PREFIX_PUB.inc(outcome="shipped")
        return 1


@dataclass
class PrefixEntry:
    """One published prefix: deepest token depth `n_tokens` at `owner`,
    plus the KVSlice geometry a puller must match (or fall back)."""

    key: str
    owner: str
    n_tokens: int
    block_size: int
    kv_dtype: str
    n_layers: int = 0
    kv_heads: int = 0
    head_dim: int = 0
    adapter: int = 0
    blocks: int = 1  # ledger blocks this entry accounts for on the owner
    expires_at: float = 0.0
    pins: int = 0
    dead: bool = False  # owner invalidated while pinned; drop at unpin
    epoch: int = 0  # owner epoch that published it; 0 = in-process (unfenced)


@dataclass(frozen=True)
class PrefixLedger:
    """Balanced-accounting snapshot: published blocks per owner."""

    blocks: dict = field(default_factory=dict)
    entries: int = 0
    pinned: int = 0


class FleetPrefixIndex:
    """Fleet-scoped map: digest(adapter, token-prefix) -> PrefixEntry.

    Not thread-safe by design — it lives on the admission path of one
    router (same single-threaded discipline as `FleetRouter` itself).
    Entries are hints: the owner re-validates on PREFIXREQ, so a stale
    entry costs one miss round-trip, never correctness.
    """

    def __init__(
        self,
        *,
        ttl_s: float = 300.0,
        max_entries: int = 4096,
        clock=time.monotonic,
    ) -> None:
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self._clock = clock
        self._entries: dict[str, PrefixEntry] = {}  # insertion order = LRU
        self._block_sizes: set[int] = set()
        self.published_total = 0
        self.evicted_total = 0
        # Current owner epoch per gossiping owner; entries stamped with an
        # older epoch are fenced (the owner restarted or was replaced —
        # its old publishes describe a cache that no longer exists).
        self.owner_epoch: dict[str, int] = {}
        self.fenced_total = 0

    # -- publish / withdraw -------------------------------------------------

    def publish(
        self,
        material,
        owner: str,
        *,
        n_tokens: int,
        block_size: int,
        kv_dtype: str,
        n_layers: int = 0,
        kv_heads: int = 0,
        head_dim: int = 0,
        adapter: int = 0,
        blocks: int = 1,
    ) -> PrefixEntry:
        key = prefix_digest(material, adapter)
        now = self._clock()
        ent = self._entries.get(key)
        if ent is not None and not ent.dead:
            # Refresh: newest publisher wins the owner slot (rebalance moves
            # blocks around); bump expiry and LRU position.
            ent.owner = owner
            ent.expires_at = now + self.ttl_s
            ent.kv_dtype = str(kv_dtype)
            ent.block_size = int(block_size)
            ent.blocks = int(blocks)
            self._entries[key] = self._entries.pop(key)
            return ent
        ent = PrefixEntry(
            key=key,
            owner=str(owner),
            n_tokens=int(n_tokens),
            block_size=int(block_size),
            kv_dtype=str(kv_dtype),
            n_layers=int(n_layers),
            kv_heads=int(kv_heads),
            head_dim=int(head_dim),
            adapter=int(adapter),
            blocks=int(blocks),
            expires_at=now + self.ttl_s,
        )
        self._entries[key] = ent
        self._block_sizes.add(int(block_size))
        self.published_total += 1
        self._evict_over_capacity()
        return ent

    def withdraw(self, material, adapter: int = 0, *, owner: str | None = None,
                 reason: str = "owner_evicted") -> bool:
        """The owning engine LRU-dropped these blocks (on_prefix_evict)."""
        key = prefix_digest(material, adapter)
        ent = self._entries.get(key)
        if ent is None or (owner is not None and ent.owner != owner):
            return False
        self._drop(ent, reason)
        return True

    # -- gossip ingest (wire path: digests, not token material) -------------

    def set_owner_epoch(self, owner: str, epoch: int) -> int:
        """Adopt a new owner epoch and fence every entry the owner
        published under an older one.  Returns entries dropped (pinned
        entries go dead and drop at unpin — never under a live pull)."""
        epoch = int(epoch)
        cur = self.owner_epoch.get(owner, 0)
        self.owner_epoch[owner] = max(cur, epoch)
        victims = [
            e for e in self._entries.values()
            if e.owner == owner and e.epoch < epoch
        ]
        dropped = 0
        for ent in victims:
            before = len(self._entries)
            self._drop(ent, "epoch_fence")
            dropped += before - len(self._entries)
            self.fenced_total += 1
            _M_EPOCH_FENCES.inc()
        if victims:
            JOURNAL.record(
                "fleet", "prefix.epoch_fence",
                owner=owner, epoch=epoch, fenced=len(victims), dropped=dropped,
            )
        return dropped

    def _epoch_admits(self, owner: str, epoch: int) -> bool:
        cur = self.owner_epoch.get(owner, 0)
        if int(epoch) < cur:
            self.fenced_total += 1
            _M_EPOCH_FENCES.inc()
            return False
        if int(epoch) > cur:
            self.set_owner_epoch(owner, epoch)
        return True

    def epoch_ok(self, ent: PrefixEntry) -> bool:
        """Pull-time fence: reject (and drop) an entry stamped with a
        superseded owner epoch — the owner behind it is not the process
        that published it, so its answer could be wrong KV."""
        if ent.epoch >= self.owner_epoch.get(ent.owner, 0):
            return True
        self.fenced_total += 1
        _M_EPOCH_FENCES.inc()
        self._drop(ent, "epoch_fence")
        return False

    def ingest_publish(self, owner: str, epoch: int, ev: dict) -> bool:
        """Apply one wire publish event (keyed by digest — the token
        material never crosses; the owner re-walks its own store on
        PREFIXREQ, so a bogus digest costs one miss, never wrong KV)."""
        if not self._epoch_admits(owner, epoch):
            _M_PREFIX_PUB.inc(outcome="fenced")
            return False
        key = str(ev.get("key", ""))
        if not key or int(ev.get("n_tokens", 0)) <= 0:
            return False
        now = self._clock()
        ent = self._entries.get(key)
        if ent is not None and not ent.dead:
            ent.owner = str(owner)
            ent.epoch = int(epoch)
            ent.n_tokens = int(ev.get("n_tokens", ent.n_tokens))
            ent.kv_dtype = str(ev.get("kv_dtype", ent.kv_dtype))
            ent.block_size = int(ev.get("block_size", ent.block_size))
            ent.blocks = int(ev.get("blocks", ent.blocks))
            ent.expires_at = now + self.ttl_s
            self._entries[key] = self._entries.pop(key)
        else:
            ent = PrefixEntry(
                key=key,
                owner=str(owner),
                n_tokens=int(ev.get("n_tokens", 0)),
                block_size=int(ev.get("block_size", 0)),
                kv_dtype=str(ev.get("kv_dtype", "")),
                n_layers=int(ev.get("n_layers", 0)),
                kv_heads=int(ev.get("kv_heads", 0)),
                head_dim=int(ev.get("head_dim", 0)),
                adapter=int(ev.get("adapter", 0)),
                blocks=int(ev.get("blocks", 1)),
                expires_at=now + self.ttl_s,
                epoch=int(epoch),
            )
            self._entries[key] = ent
            if ent.block_size > 0:
                self._block_sizes.add(ent.block_size)
            self.published_total += 1
            self._evict_over_capacity()
        _M_PREFIX_PUB.inc(outcome="ingested")
        return True

    def ingest_withdraw(self, owner: str, epoch: int, ev: dict) -> bool:
        """Apply one wire withdraw event (owner-guarded, epoch-fenced)."""
        if not self._epoch_admits(owner, epoch):
            _M_PREFIX_PUB.inc(outcome="fenced")
            return False
        ent = self._entries.get(str(ev.get("key", "")))
        if ent is None or ent.owner != owner:
            return False
        self._drop(ent, "owner_evicted")
        _M_PREFIX_PUB.inc(outcome="withdrawn")
        return True

    def ingest_digest(self, owner: str, epoch: int, events: list) -> dict:
        """Anti-entropy: the owner shipped its FULL holdings.  Drop every
        entry of that owner the digest no longer names (divergence from a
        partition heals here), then upsert the digest's events."""
        if not self._epoch_admits(owner, epoch):
            _M_PREFIX_PUB.inc(outcome="fenced")
            return {"ingested": 0, "dropped": 0}
        held = {str(ev.get("key", "")) for ev in events}
        victims = [
            e for e in self._entries.values()
            if e.owner == owner and e.key not in held
        ]
        dropped = 0
        for ent in victims:
            before = len(self._entries)
            self._drop(ent, "anti_entropy")
            dropped += before - len(self._entries)
        ingested = 0
        for ev in events:
            ingested += bool(self.ingest_publish(owner, epoch, ev))
        JOURNAL.record(
            "fleet", "prefix.anti_entropy",
            owner=owner, epoch=int(epoch),
            held=len(held), ingested=ingested, dropped=dropped,
        )
        return {"ingested": ingested, "dropped": dropped}

    def _drop(self, ent: PrefixEntry, reason: str) -> None:
        if ent.pins > 0:
            # Never race an in-flight pull: keep the entry until unpin.
            ent.dead = True
            return
        self._entries.pop(ent.key, None)
        self.evicted_total += 1
        _M_PREFIX_EVICT.inc(reason=reason)

    def _evict_over_capacity(self) -> None:
        while len(self._entries) > self.max_entries:
            victim = None
            for ent in self._entries.values():  # oldest first
                if ent.pins == 0:
                    victim = ent
                    break
            if victim is None:
                return  # everything pinned; capacity is advisory then
            self._drop(victim, "capacity")

    # -- lookup -------------------------------------------------------------

    def block_sizes(self):
        return sorted(self._block_sizes)

    def chain_for_tokens(self, tokens, adapter: int = 0):
        """Candidate chain [(n_tokens, material)] shallow->deep for a real
        token prompt, one rung per whole block at every granularity the
        fleet has published (paged block sizes and dense buckets alike)."""
        n = len(tokens)
        depths: set[int] = set()
        for bs in self._block_sizes:
            if bs <= 0:
                continue
            # A usable prefix must leave >= 1 token to prefill from.
            d = bs
            while d < n:
                depths.add(d)
                d += bs
        return [(d, tuple(tokens[:d])) for d in sorted(depths)]

    def _live(self, ent: PrefixEntry | None, now: float) -> PrefixEntry | None:
        if ent is None or ent.dead:
            return None
        if ent.expires_at <= now:
            self._drop(ent, "ttl")
            return None
        return ent

    def deepest(self, chain, adapter: int = 0, *, compatible=None):
        """Deepest live entry along the chain that passes `compatible(ent)`.
        Chain rungs are independent candidates (contiguity is the owner's
        problem — it re-walks its own store on PREFIXREQ)."""
        now = self._clock()
        for n_tokens, material in reversed(list(chain)):
            ent = self._live(self._entries.get(prefix_digest(material, adapter)), now)
            if ent is None or ent.n_tokens != n_tokens:
                continue
            if compatible is not None and not compatible(ent):
                continue
            return ent
        return None

    def survey(self, chain, adapter: int = 0) -> dict:
        """Per-owner deepest published depth along the chain, as
        {owner: (n_tokens, blocks)} — the router's depth-aware affinity
        signal."""
        now = self._clock()
        out: dict[str, tuple[int, int]] = {}
        for n_tokens, material in chain:
            ent = self._live(self._entries.get(prefix_digest(material, adapter)), now)
            if ent is None:
                continue
            best = out.get(ent.owner)
            if best is None or n_tokens > best[0]:
                depth_blocks = (
                    n_tokens // ent.block_size if ent.block_size > 0 else 1
                )
                out[ent.owner] = (n_tokens, max(1, depth_blocks))
        return out

    # -- pin / sweep / invalidate ------------------------------------------

    def pin(self, key: str) -> bool:
        ent = self._entries.get(key)
        if ent is None or ent.dead:
            return False
        ent.pins += 1
        return True

    def unpin(self, key: str) -> None:
        ent = self._entries.get(key)
        if ent is None:
            return
        ent.pins = max(0, ent.pins - 1)
        if ent.dead and ent.pins == 0:
            self._entries.pop(ent.key, None)
            self.evicted_total += 1
            _M_PREFIX_EVICT.inc(reason="invalidated")

    def sweep(self, now: float | None = None) -> int:
        """TTL sweep; returns entries dropped.  Pinned entries survive."""
        now = self._clock() if now is None else now
        expired = [e for e in self._entries.values() if e.expires_at <= now]
        dropped = 0
        for ent in expired:
            before = len(self._entries)
            self._drop(ent, "ttl")
            dropped += before - len(self._entries)
        return dropped

    def invalidate_owner(self, owner: str, *, reason: str = "invalidated") -> int:
        """Owner drained / removed / rebalanced: its entries are garbage.
        Unpinned entries drop now; pinned ones are marked dead and drop at
        unpin (never under an in-flight pull)."""
        victims = [e for e in self._entries.values() if e.owner == owner]
        dropped = 0
        for ent in victims:
            before = len(self._entries)
            self._drop(ent, reason)
            dropped += before - len(self._entries)
        if victims:
            JOURNAL.record(
                "fleet", "prefix.invalidate",
                owner=owner,
                entries=len(victims),
                dropped=dropped,
                reason=reason,
            )
        return dropped

    # -- accounting ---------------------------------------------------------

    def ledger(self) -> PrefixLedger:
        blocks: dict[str, int] = {}
        pinned = 0
        for ent in self._entries.values():
            blocks[ent.owner] = blocks.get(ent.owner, 0) + max(1, ent.blocks)
            if ent.pins > 0:
                pinned += 1
        return PrefixLedger(blocks=blocks, entries=len(self._entries), pinned=pinned)

    def __len__(self) -> int:
        return len(self._entries)

    def note_hit(self, source: str) -> None:
        _M_PREFIX_HITS.inc(source=source)


class LocalPrefixSource:
    """Pull leg for an owner replica in the same process.  Still round-trips
    the wire encoding so CRC + quantized-geometry validation is identical to
    the socket path (a corrupt export surfaces as WireFormatError -> cold
    prefill, exactly like a corrupt frame would)."""

    def __init__(self, name: str, engine) -> None:
        self.name = name
        self.engine = engine

    def pull(self, tokens, *, max_tokens=None, adapter: int = 0,
             nonce: int = 0, epoch: int = 0):
        export = getattr(self.engine, "export_prefix_kv", None)
        if export is None:
            return None
        kv = export(tokens, max_tokens=max_tokens, adapter=adapter)
        if kv is None:
            return None
        from k8s_dra_driver_tpu.models.serve import KVSlice, WireFormatError

        try:
            _, out = KVSlice.from_wire(kv.to_wire(nonce))
        except WireFormatError:
            return None
        return out


class RemotePrefixSource:
    """Pull leg over a transport `PeerLink`: PREFIXREQ out, PREFIXKV or
    PREFIXMISS back, bounded by the link's breaker, heartbeat liveness, and
    a pull deadline.  Every failure mode returns None (cold prefill)."""

    def __init__(self, name: str, link, *, peer_pump=None,
                 pull_timeout_s: float = 5.0, clock=time.monotonic) -> None:
        self.name = name
        self.link = link
        self.peer_pump = peer_pump
        self.pull_timeout_s = float(pull_timeout_s)
        self._clock = clock
        self.last_miss_reason: str | None = None

    def pull(self, tokens, *, max_tokens=None, adapter: int = 0,
             nonce: int = 0, epoch: int = 0):
        import struct

        from k8s_dra_driver_tpu.models import transport as T
        from k8s_dra_driver_tpu.models.serve import KVSlice, WireFormatError

        decode_errors = (WireFormatError, struct.error, ValueError,
                         KeyError, UnicodeDecodeError)

        self.last_miss_reason = None
        link = self.link
        if link.dead or not link.breaker.allow():
            self.last_miss_reason = "breaker"
            return None
        try:
            link.send_json(
                T.PREFIXREQ,
                {
                    "nonce": int(nonce),
                    "tokens": [int(t) for t in tokens],
                    "max_tokens": None if max_tokens is None else int(max_tokens),
                    "adapter": int(adapter),
                    "epoch": int(epoch),
                },
            )
        except (T.TransportDownError, T.PeerDiedError, OSError):
            return None
        deadline = self._clock() + self.pull_timeout_s
        while True:
            try:
                link.pump()
                if self.peer_pump is not None and not link.dead:
                    self.peer_pump()
            except (T.TransportDownError, T.PeerDiedError, OSError):
                return None
            body = link.take(T.PREFIXKV)
            if body is not None:
                try:
                    meta, wire = T.decode_meta_frame(body)
                    if int(meta.get("nonce", -1)) != int(nonce):
                        continue  # stale reply from a timed-out earlier pull
                    # Epoch fence: an answer stamped by a different owner
                    # process than the one that published the entry is a
                    # typed miss — never installable KV.
                    got_epoch = int(meta.get("epoch", 0))
                    if epoch and got_epoch and got_epoch != int(epoch):
                        self.last_miss_reason = "epoch"
                        return None
                    rid, kv = KVSlice.from_wire(wire)
                except decode_errors:
                    return None
                if rid != int(nonce):
                    continue
                return kv
            body = link.take(T.PREFIXMISS)
            if body is not None:
                try:
                    meta, _ = T.decode_meta_frame(body)
                except decode_errors:
                    return None
                if int(meta.get("nonce", -1)) == int(nonce):
                    self.last_miss_reason = str(meta.get("reason", "miss"))
                    return None
                continue
            if link.dead or self._clock() >= deadline:
                return None
            if self.peer_pump is None:
                # Not a retry loop: the except arm above RETURNS (cold-
                # prefill fallback) — this is the deadline-bounded socket
                # poll, same cadence as transport.py's recv waits.
                time.sleep(0.002)  # lint: ignore[sleep-retry]

    @property
    def dead(self) -> bool:
        return bool(self.link.dead)

    @property
    def available(self) -> bool:
        """Reachability WITHOUT consuming a breaker probe: a dead link or
        an open breaker degrades placement to local-only — the tier never
        dials into a peer the transport already knows is unreachable."""
        return not self.link.dead and self.link.breaker.state != "open"


class FleetPrefixTier:
    """Admission-time consumer bound to one `FleetRouter` (via
    `router.attach_prefix_tier`).  `prepare()` runs just before
    `engine.submit()`: it classifies the admission as a local hit, pulls
    remote prefix KV into the engine's cached-blocks path, or leaves the
    request to cold prefill.  Any exception inside prepare is contained —
    the tier can only ever cost, never fail, an admission."""

    def __init__(
        self,
        index: FleetPrefixIndex | None = None,
        *,
        clock=time.monotonic,
        pull_timeout_s: float = 5.0,
        min_remote_tokens: int = 1,
    ) -> None:
        self.index = index if index is not None else FleetPrefixIndex(clock=clock)
        self._clock = clock
        self.pull_timeout_s = float(pull_timeout_s)
        self.min_remote_tokens = int(min_remote_tokens)
        self._sources: dict[str, object] = {}
        self._nonce = 0
        self.counts = {"local": 0, "remote": 0, "cold": 0}
        self.fallbacks: dict[str, int] = {}
        # Ledger-gated pull admission (models/disagg.py): a remote pull
        # reserves its blocks against the KV-demand ledger for the
        # transfer window.  ``reserve_pull(nonce, blocks)`` -> True
        # (reserved), False (over-demand: fall back cold), None (bypass —
        # headroom unaccountable, stand aside like stream admission does).
        self.pull_gate = None
        self._gossip_links: dict[str, object] = {}
        self._owner_cfg: dict[str, dict] = {}
        self.gossip_decode_drops = 0

    # -- wiring -------------------------------------------------------------

    def add_source(self, name: str, source) -> None:
        self._sources[name] = source

    def remove_source(self, name: str) -> None:
        self._sources.pop(name, None)

    def attach_remote_owner(self, name: str, link, *, peer_pump=None,
                            pull_timeout_s: float | None = None) -> None:
        """Wire a remote owner worker into the tier: a pull source over
        its transport link, gossip ingestion from its PREFIXPUB/PREFIXWDL
        inbox, and epoch-fenced ownership — the owner epoch bumps on
        every (re)connect and an anti-entropy resync is requested so the
        index converges to what the (possibly replacement) process holds."""
        cfg = {
            "peer_pump": peer_pump,
            "pull_timeout_s": (self.pull_timeout_s if pull_timeout_s is None
                               else float(pull_timeout_s)),
        }
        self._owner_cfg[name] = cfg
        self.add_source(name, RemotePrefixSource(
            name, link, peer_pump=cfg["peer_pump"],
            pull_timeout_s=cfg["pull_timeout_s"], clock=self._clock,
        ))
        self._gossip_links[name] = link
        self.index.set_owner_epoch(name, self.index.owner_epoch.get(name, 0) + 1)
        on_reconnect = getattr(link, "on_reconnect", None)
        if on_reconnect is not None:
            on_reconnect.append(
                lambda lk, n=name: self._on_owner_reconnect(n, lk)
            )
        self._send_resync(name, link)

    def detach_remote_owner(self, name: str) -> None:
        self._gossip_links.pop(name, None)
        self._owner_cfg.pop(name, None)
        self.remove_source(name)

    def _on_owner_reconnect(self, name: str, link) -> None:
        """Reconnect = spawn or replacement: bump the owner epoch (fences
        every stale entry), restore the pull source if a mid-pull death
        removed it, and ask the worker for its full anti-entropy digest."""
        self.index.set_owner_epoch(name, self.index.owner_epoch.get(name, 0) + 1)
        cfg = self._owner_cfg.get(name)
        if cfg is not None and name not in self._sources:
            self.add_source(name, RemotePrefixSource(
                name, link, peer_pump=cfg["peer_pump"],
                pull_timeout_s=cfg["pull_timeout_s"], clock=self._clock,
            ))
        self._send_resync(name, link)

    def _send_resync(self, name: str, link) -> None:
        from k8s_dra_driver_tpu.models import transport as T

        try:
            link.send_json(T.CONTROL, {
                "op": "prefix_resync",
                "epoch": int(self.index.owner_epoch.get(name, 0)),
            })
        except (T.TransportDownError, T.PeerDiedError, OSError):
            pass  # the next reconnect retries; entries stay fenced until then

    def owner_available(self, name: str) -> bool:
        """Placement signal: False when the owner sits behind a dead link
        or an open breaker (degrade to local-only instead of routing at
        an unreachable owner)."""
        source = self._sources.get(name)
        if source is not None:
            return bool(getattr(source, "available", True))
        link = self._gossip_links.get(name)
        if link is not None:
            return not link.dead and link.breaker.state != "open"
        return True

    def drain_gossip(self) -> int:
        """Ingest buffered PREFIXPUB/PREFIXWDL frames from every attached
        owner link.  Pure host-side dict work on the router tick; corrupt
        frames are dropped whole (typed, counted), never partially applied."""
        from k8s_dra_driver_tpu.models import transport as T

        applied = 0
        for name, link in list(self._gossip_links.items()):
            while True:
                body = link.take(T.PREFIXPUB)
                if body is None:
                    break
                applied += self._ingest_pub(name, body)
            while True:
                body = link.take(T.PREFIXWDL)
                if body is None:
                    break
                applied += self._ingest_wdl(name, body)
        return applied

    def _ingest_pub(self, name: str, body: bytes) -> int:
        try:
            doc, epoch, seq = decode_prefix_gossip(body)
        except PrefixGossipError as exc:
            self._gossip_drop(name, exc)
            return 0
        events = doc.get("events", [])
        if doc.get("full"):
            res = self.index.ingest_digest(name, epoch, list(events))
            return int(res.get("ingested", 0))
        n = 0
        for ev in events:
            if isinstance(ev, dict):
                n += bool(self.index.ingest_publish(name, epoch, ev))
        return n

    def _ingest_wdl(self, name: str, body: bytes) -> int:
        try:
            doc, epoch, _seq = decode_prefix_gossip(body)
        except PrefixGossipError as exc:
            self._gossip_drop(name, exc)
            return 0
        n = 0
        for ev in doc.get("events", []):
            if isinstance(ev, dict):
                n += bool(self.index.ingest_withdraw(name, epoch, ev))
        return n

    def _gossip_drop(self, name: str, exc: PrefixGossipError) -> None:
        self.gossip_decode_drops += 1
        _M_PREFIX_PUB.inc(outcome="decode_drop")
        JOURNAL.record_lazy(
            "fleet", "prefix.gossip_drop", correlation=f"prefix-owner-{name}",
            attrs=lambda: dict(error=str(exc), epoch=exc.epoch, seq=exc.seq),
        )

    def bind_engine(self, name: str, engine) -> None:
        """Attach publish/evict hooks so the engine feeds the index as it
        stores prefix blocks, and register a local pull source for it."""
        geom_fn = getattr(engine, "prefix_geometry", None)
        if geom_fn is None:
            return
        geom = dict(geom_fn())
        index = self.index

        def _on_store(material, n_tokens, adapter=0):
            index.publish(
                material,
                name,
                n_tokens=int(n_tokens),
                block_size=int(geom.get("block_size", 0)),
                kv_dtype=str(geom.get("kv_dtype", "")),
                n_layers=int(geom.get("n_layers", 0)),
                kv_heads=int(geom.get("kv_heads", 0)),
                head_dim=int(geom.get("head_dim", 0)),
                adapter=int(adapter),
                blocks=1,  # one store block per published depth rung
            )

        def _on_evict(material, adapter=0):
            index.withdraw(material, adapter, owner=name)

        engine.on_prefix_store = _on_store
        engine.on_prefix_evict = _on_evict
        if getattr(engine, "export_prefix_kv", None) is not None:
            self.add_source(name, LocalPrefixSource(name, engine))

    def unbind_engine(self, name: str, engine=None) -> None:
        if engine is not None:
            if getattr(engine, "on_prefix_store", None) is not None:
                engine.on_prefix_store = None
            if getattr(engine, "on_prefix_evict", None) is not None:
                engine.on_prefix_evict = None
        self.remove_source(name)

    def on_replica_gone(self, name: str, engine=None) -> None:
        """Scale-down / rebalance / death: invalidate everything it owned."""
        self.unbind_engine(name, engine)
        self.index.invalidate_owner(name)

    def tick(self) -> None:
        """Router tick hook: gossip ingest + TTL sweep (pure dict work,
        no device syncs)."""
        self.drain_gossip()
        self.index.sweep()

    # -- admission ----------------------------------------------------------

    def _note_fallback(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def _compatible(self, geom: dict, rep_name: str, local_depth: int,
                    unreachable: list | None = None):
        quantized_dtypes = ("int8", "int4")

        def check(ent: PrefixEntry) -> bool:
            if ent.owner == rep_name:
                return False
            if not self.owner_available(ent.owner):
                # Breaker-open / dead link: degrade to local-only placement
                # rather than dialing a pull into an unreachable owner.
                if unreachable is not None:
                    unreachable.append(ent.owner)
                return False
            if ent.n_tokens <= max(local_depth, self.min_remote_tokens - 1):
                return False
            if geom.get("n_layers") and ent.n_layers and ent.n_layers != geom["n_layers"]:
                return False
            if geom.get("kv_heads") and ent.kv_heads and ent.kv_heads != geom["kv_heads"]:
                return False
            if geom.get("head_dim") and ent.head_dim and ent.head_dim != geom["head_dim"]:
                return False
            # Bit-equality rule: pool dtypes must match exactly (cross-dtype
            # conversion is not bit-stable); quantized pools additionally
            # require the same block granularity because scales are
            # per-block.  Float payloads may re-block: the receiver installs
            # whole receiver-blocks, so it needs at least one.
            if ent.kv_dtype != geom.get("kv_dtype"):
                return False
            if ent.kv_dtype in quantized_dtypes:
                if ent.block_size != geom.get("block_size"):
                    return False
            else:
                bs = int(geom.get("block_size", 0) or 0)
                if bs > 0 and ent.n_tokens // bs < 1:
                    return False
            return True

        return check

    def prepare(self, rep_name: str, engine, prompt, *, max_tokens=None,
                adapter: int = 0, chain=None) -> str:
        """Classify + warm one admission.  Returns 'local' | 'remote' |
        'cold'.  Never raises past itself."""
        try:
            return self._prepare(rep_name, engine, prompt,
                                 max_tokens=max_tokens, adapter=adapter,
                                 chain=chain)
        except Exception as exc:  # containment: tier failures cost, not fail
            JOURNAL.record("fleet", "prefix.prepare_error", replica=rep_name,
                           error=f"{type(exc).__name__}: {exc}")
            self._note_fallback("error")
            self.counts["cold"] += 1
            return "cold"

    def _prepare(self, rep_name, engine, prompt, *, max_tokens, adapter, chain):
        depth_fn = getattr(engine, "local_prefix_depth", None)
        geom_fn = getattr(engine, "prefix_geometry", None)
        inject = getattr(engine, "inject_prefix_kv", None)
        local_depth = int(depth_fn(prompt, adapter)) if depth_fn is not None else 0
        if geom_fn is None or inject is None:
            if local_depth > 0:
                self.index.note_hit("local")
                self.counts["local"] += 1
                return "local"
            self.counts["cold"] += 1
            return "cold"
        geom = dict(geom_fn())
        if chain is None:
            chain = self.index.chain_for_tokens(prompt, adapter)
        unreachable: list = []
        ent = self.index.deepest(
            chain, adapter,
            compatible=self._compatible(geom, rep_name, local_depth,
                                        unreachable=unreachable))
        if ent is None:
            if unreachable:
                self._note_fallback("breaker_open")
            if local_depth > 0:
                self.index.note_hit("local")
                self.counts["local"] += 1
                return "local"
            self.counts["cold"] += 1
            return "cold"
        if not self.index.epoch_ok(ent):
            # Stale-epoch entry survived ingest fencing (e.g. a pinned
            # hint): typed miss, never a pull at the wrong process.
            self._note_fallback("epoch_fence")
            return self._after_failed_pull(local_depth)
        source = self._sources.get(ent.owner)
        if source is None:
            self._note_fallback("no_source")
            return self._after_failed_pull(local_depth)
        self._nonce += 1
        nonce = self._nonce
        # A pull is demand too: reserve its receiver blocks against the
        # KV-demand ledger for the transfer window, or fall back cold.
        bs = int(geom.get("block_size", 0) or 0)
        need = -(-ent.n_tokens // bs) if bs > 0 else max(1, int(ent.blocks))
        reserved = False
        if self.pull_gate is not None:
            verdict = self.pull_gate.reserve_pull(nonce, need)
            if verdict is False:
                _M_PULL_ADMISSION.inc(outcome="refused")
                self._note_fallback("pull_admission")
                JOURNAL.record(
                    "fleet", "prefix.pull", correlation=f"prefix-pull-{nonce}",
                    owner=ent.owner, blocks=need, outcome="refused",
                )
                return self._after_failed_pull(local_depth)
            reserved = verdict is True
            _M_PULL_ADMISSION.inc(
                outcome="admitted" if reserved else "bypass")
        pinned = self.index.pin(ent.key)
        t0 = self._clock()
        injected = 0
        outcome = "miss"
        try:
            kv = source.pull(prompt, max_tokens=max_tokens, adapter=adapter,
                             nonce=nonce, epoch=ent.epoch)
            if kv is None:
                miss_reason = getattr(source, "last_miss_reason", None)
                if getattr(source, "dead", False):
                    # Owner died mid-pull: its whole index footprint is
                    # garbage now, not just this entry.
                    self.on_replica_gone(ent.owner)
                    outcome = "owner_dead"
                elif miss_reason == "epoch":
                    # Answered by the wrong owner epoch: typed miss.
                    _M_EPOCH_FENCES.inc()
                    self.index._drop(ent, "epoch_fence")
                    outcome = "epoch_fence"
                else:
                    outcome = "miss"
                self._note_fallback(outcome)
                return self._after_failed_pull(local_depth)
            injected = int(inject(prompt, kv, adapter=adapter) or 0)
            outcome = "injected" if injected > 0 else "inject"
        finally:
            if reserved:
                self.pull_gate.release_pull(nonce)
            if pinned:
                self.index.unpin(ent.key)
            _M_PREFIX_PULL.observe(max(0.0, self._clock() - t0))
            JOURNAL.record(
                "fleet", "prefix.pull", correlation=f"prefix-pull-{nonce}",
                owner=ent.owner, n_tokens=int(ent.n_tokens), blocks=need,
                outcome=outcome,
            )
        if injected <= 0:
            self._note_fallback("inject")
            return self._after_failed_pull(local_depth)
        self.index.note_hit("remote")
        self.counts["remote"] += 1
        return "remote"

    def _after_failed_pull(self, local_depth: int) -> str:
        if local_depth > 0:
            self.index.note_hit("local")
            self.counts["local"] += 1
            return "local"
        self.counts["cold"] += 1
        return "cold"
