"""Closed-loop fleet autoscaler: replica count tracks offered load.

This is the controller that closes ROADMAP item 2's loop.  The sensor
half exists (PR 6: per-replica ``EngineStats`` verdicts, fleet queue
depth) and the actuator half exists (PR 7: ``add_replica`` with disjoint
id strides, ``drain()`` → live-migration evacuation with zero dropped
streams).  :class:`FleetAutoscaler` sits between them, turning load
signals into scaling actions under a hard replica budget — ParvaGPU's
SLO-aware sizing framing (arxiv 2409.14447), where the headline metric
is SLO attainment at a replica count, not raw tokens/s.

Control law, evaluated once per :meth:`tick`:

* **Sense.**  Utilization = busy slots / total slots across the
  ADMITTABLE replicas (``FleetRouter.admittable_replicas()`` — draining
  and breaker-open replicas don't count as capacity), plus the fleet
  front-door queue depth (or the driver's backlog, passed in).
* **Vote.**  Pressure above ``target_util_high`` or a queue deeper than
  ``queue_high`` per live replica votes up; utilization below
  ``target_util_low`` with an empty queue votes down; anything else
  resets both streaks.
* **Hysteresis + cooldown.**  An action fires only after ``up_ticks``
  (resp. ``down_ticks``) CONSECUTIVE votes, and never within
  ``cooldown_s`` of the previous action — a breaker flap or a one-tick
  queue spike cannot thrash the fleet.
* **Act, bounded.**  Targets clamp to ``[min_replicas, max_replicas]``.
  Scale-up runs the caller-supplied engine factory (fault hooks
  ``spawn_fail``/``spawn_latency_ms`` fire BEFORE it — a failed spawn
  journals, backs off ``spawn_backoff_s`` and never half-registers),
  registers via ``add_replica`` and replays parked overflow.  Scale-down
  picks the least-loaded admittable replica (never SUSPECT/EVACUATING/
  DRAINED) and drains it through the evacuation path — zero dropped
  streams.  Every action journals under ONE correlation id
  (``scale-<router_seq>-<n>``) spanning decision → spawn/drain →
  resumed, the same scheme as evacuations.

The controller is host-only (dict/clock arithmetic over ``stats()``
snapshots — ``tools/perf_smoke.py check_autoscaler_overhead`` pins that
a no-op autoscaler adds ZERO device work) and jax-free, so
``/debug/autoscale`` renders from control-plane binaries.  Drive it
explicitly (``autoscaler.tick()`` from a replay/bench loop) or attach it
to the router's tick hooks (:meth:`attach`) so ``FleetRouter.pump``
drives it — never both.
"""

from __future__ import annotations

import os
import time
import weakref
from dataclasses import dataclass

from k8s_dra_driver_tpu.models.fleet import DRAINED, FleetRouter
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_M_REPLICAS = REGISTRY.gauge(
    "tpu_autoscale_replicas",
    "autoscaler replica counts, by kind (target vs actual)",
)
_M_EVENTS = REGISTRY.counter(
    "tpu_autoscale_events_total",
    "autoscaler scaling actions, by direction and reason",
)
_M_DECISION = REGISTRY.histogram(
    "tpu_autoscale_decision_seconds",
    "wall-clock seconds spent per autoscaler tick decision",
    buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 1e-1),
)
_M_ATTAIN = REGISTRY.gauge(
    "tpu_autoscale_slo_attainment",
    "fraction of offered requests meeting their TTFT and TPOT targets",
)

UP = "up"
DOWN = "down"
MOVE = "move"

# Flag for the remote-spawn path: when truthy, scale-ups run the
# transport-backed factory (a worker process / PoolWorker rig behind a
# PeerLink) instead of constructing an engine in the supervisor.
ENV_REMOTE_WORKERS = "DRA_REMOTE_WORKERS"


def select_engine_factory(local_factory, remote_factory=None,
                          environ=os.environ):
    """Flagged engine-factory selection for the spawn path.

    ``local_factory`` builds in-supervisor engines (the default);
    ``remote_factory`` builds transport-worker-backed replicas — usually
    :func:`k8s_dra_driver_tpu.models.transport.make_remote_engine_factory`
    over the ``worker_main`` rig.  :data:`ENV_REMOTE_WORKERS` picks:
    truthy ("1"/"true"/"yes"/"on") selects the remote factory and raises
    loudly when none was wired (a production flag must never silently
    degrade to local spawning); anything else selects local."""
    raw = environ.get(ENV_REMOTE_WORKERS, "").strip().lower()
    if raw in ("1", "true", "yes", "on"):
        if remote_factory is None:
            raise ValueError(
                f"{ENV_REMOTE_WORKERS} is set but no remote engine factory "
                "was provided"
            )
        return remote_factory
    return local_factory


@dataclass(frozen=True)
class AutoscalerPolicy:
    """Control-law thresholds.  All deterministic, all host-side."""

    min_replicas: int = 1
    max_replicas: int = 8
    target_util_high: float = 0.85  # busy-slot fraction that votes up
    target_util_low: float = 0.30   # busy-slot fraction that votes down
    queue_high: int = 4             # queue depth per live replica voting up
    up_ticks: int = 2               # consecutive up-votes before acting
    down_ticks: int = 8             # consecutive down-votes before acting
    cooldown_s: float = 20.0        # min seconds between scaling actions
    max_step: int = 1               # replicas added/removed per action
    spawn_backoff_s: float = 10.0   # pause after a failed spawn

    def __post_init__(self):
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})"
            )


class FleetAutoscaler:
    """The controller between the stats feed and the scaling actuators.

    ``engine_factory`` is a zero-argument callable returning a fresh
    Engine-protocol replica (the caller owns device placement, params,
    clocks).  ``clock`` defaults to the router's — one clock rules the
    whole loop, so simulated-time replays compress cooldowns too.
    """

    def __init__(
        self,
        router: FleetRouter,
        engine_factory,
        policy: AutoscalerPolicy | None = None,
        clock=None,
        fault_injector=None,
        name_prefix: str = "as",
        burn_monitor=None,
    ):
        self.router = router
        self.engine_factory = engine_factory
        self.policy = policy or AutoscalerPolicy()
        # Optional obs_plane.SloBurnRateMonitor: while it alerts, the
        # error budget is burning on every window — treated as scale-up
        # pressure even when utilization alone wouldn't vote.
        self.burn_monitor = burn_monitor
        self.clock = clock or router.clock
        self.fault_injector = (
            fault_injector if fault_injector is not None
            else router.fault_injector
        )
        self.name_prefix = name_prefix
        self.seq = router.seq
        self.ticks = 0
        self.actions = 0
        self.spawn_failures = 0
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_t: float | None = None
        self._backoff_until: float | None = None
        self._spawn_seq = 0
        self._scale_seq = 0
        self._pending_spawns: list[dict] = []
        self._attained = 0
        self._offered = 0
        self.last_decision: dict = {}
        self._attached = False
        _LIVE_AUTOSCALERS.add(self)

    # -- wiring ------------------------------------------------------------

    def attach(self) -> "FleetAutoscaler":
        """Register on the router's tick hooks so ``FleetRouter.pump``
        (or ``tick()``) drives the control loop.  Don't also call
        :meth:`tick` from a driver loop — one drive path per loop."""
        if not self._attached:
            self.router.tick_hooks.append(self._on_router_tick)
            self._attached = True
        return self

    def _on_router_tick(self) -> None:
        self.tick()

    # -- SLO feedback ------------------------------------------------------

    def record_slo(self, attained: int, offered: int) -> None:
        """Fold one measurement window into the attainment gauge (the
        replay driver owns the per-request scoring)."""
        self._attained += int(attained)
        self._offered += int(offered)
        if self._offered:
            _M_ATTAIN.set(self._attained / self._offered)

    # -- the control loop --------------------------------------------------

    def tick(self, queue_depth: int | None = None) -> dict:
        """One sense → vote → act evaluation.  Returns the decision doc
        (also kept as ``last_decision`` for /debug/autoscale)."""
        t0 = time.perf_counter()
        now = self.clock()
        self.ticks += 1
        self._realize_spawns(now)
        depth = (
            int(queue_depth) if queue_depth is not None
            else self.router._queue_depth
        )
        admittable = self.router.admittable_replicas()
        actual = sum(1 for r in self.router.replicas if r.state != DRAINED)
        total_slots = sum(r.engine.n_slots for r in admittable)
        busy = sum(r.resident() for r in admittable)
        util = busy / total_slots if total_slots else 1.0
        vote = self._vote(util, depth, len(admittable))
        burn_forced = False
        if (
            vote != UP
            and self.burn_monitor is not None
            and self.burn_monitor.alerting
        ):
            # The SLO burn monitor says the error budget is being spent
            # past threshold on every window: that is demand pressure the
            # utilization signal can miss (e.g. slow-but-full replicas).
            vote, burn_forced = UP, True
        if vote == UP:
            self._up_streak += 1
            self._down_streak = 0
        elif vote == DOWN:
            self._down_streak += 1
            self._up_streak = 0
        else:
            self._up_streak = self._down_streak = 0
        target = actual + len(self._pending_spawns)
        action = ""
        reason = ""
        p = self.policy
        cooling = (
            self._last_action_t is not None
            and now - self._last_action_t < p.cooldown_s
        )
        backing_off = (
            self._backoff_until is not None and now < self._backoff_until
        )
        if (
            actual < p.min_replicas
            and not self._pending_spawns
            and not backing_off
        ):
            # Below the floor (e.g. a replica died and was drained):
            # hysteresis and cooldown never block restoring the minimum.
            target = p.min_replicas
            action, reason = UP, "min_replicas"
        elif (
            vote == UP and self._up_streak >= p.up_ticks
            and not cooling and not backing_off
            and actual + len(self._pending_spawns) < p.max_replicas
        ):
            target = min(
                p.max_replicas,
                actual + len(self._pending_spawns) + p.max_step,
            )
            action = UP
            if burn_forced:
                reason = "slo_burn"
            elif depth >= p.queue_high * max(1, len(admittable)):
                reason = "queue_pressure"
            else:
                reason = "overload"
        elif (
            vote == DOWN and self._down_streak >= p.down_ticks
            and not cooling
            and actual > p.min_replicas
            and not self._pending_spawns
        ):
            target = max(p.min_replicas, actual - p.max_step)
            action, reason = DOWN, "underload"
        if action == UP:
            self._scale_up(target - actual - len(self._pending_spawns),
                           reason, now)
        elif action == DOWN:
            self._scale_down(actual - target, reason, now)
        _M_REPLICAS.set(target, kind="target")
        _M_REPLICAS.set(
            sum(1 for r in self.router.replicas if r.state != DRAINED),
            kind="actual",
        )
        self.last_decision = {
            "tick": self.ticks,
            "now": round(now, 3),
            "utilization": round(util, 4),
            "queue_depth": depth,
            "admittable": len(admittable),
            "actual": actual,
            "target": target,
            "vote": vote or "hold",
            "up_streak": self._up_streak,
            "down_streak": self._down_streak,
            "cooling": cooling,
            "backing_off": backing_off,
            "pending_spawns": len(self._pending_spawns),
            "action": action or "none",
            "reason": reason,
            "burn_alert": burn_forced or (
                self.burn_monitor is not None and self.burn_monitor.alerting
            ),
        }
        _M_DECISION.observe(time.perf_counter() - t0)
        return self.last_decision

    def _vote(self, util: float, depth: int, n_admittable: int) -> str:
        p = self.policy
        if n_admittable == 0:
            return UP  # no admittable capacity at all is maximal pressure
        if util >= p.target_util_high or depth >= p.queue_high * n_admittable:
            return UP
        if util <= p.target_util_low and depth == 0:
            return DOWN
        return ""

    # -- actuators ---------------------------------------------------------

    def _mint_corr(self) -> str:
        self._scale_seq += 1
        return f"scale-{self.seq}-{self._scale_seq}"

    def _scale_up(self, n: int, reason: str, now: float) -> None:
        for _ in range(max(1, n)):
            corr = self._mint_corr()
            attempt = self._spawn_seq
            self._spawn_seq += 1
            inj = self.fault_injector
            if inj is not None:
                from k8s_dra_driver_tpu.utils.faults import SpawnFault

                try:
                    inj.maybe_fail_spawn(attempt)
                except SpawnFault as exc:
                    self.spawn_failures += 1
                    self._backoff_until = now + self.policy.spawn_backoff_s
                    self._last_action_t = now
                    self._up_streak = 0
                    _M_EVENTS.inc(direction=UP, reason="spawn_fail")
                    JOURNAL.record(
                        "autoscale", "scale_up.spawn_failed",
                        correlation=corr, attempt=attempt, error=str(exc),
                        backoff_s=self.policy.spawn_backoff_s,
                    )
                    return
            ready_at = now
            if inj is not None:
                ready_at += inj.take_spawn_latency(attempt)
            JOURNAL.record(
                "autoscale", "scale_up.begin", correlation=corr,
                attempt=attempt, reason=reason,
                ready_in_s=round(ready_at - now, 3),
            )
            self._last_action_t = now
            self._up_streak = 0
            self.actions += 1
            _M_EVENTS.inc(direction=UP, reason=reason)
            self._pending_spawns.append(
                {"corr": corr, "ready_at": ready_at, "attempt": attempt}
            )
        self._realize_spawns(now)

    def _realize_spawns(self, now: float) -> None:
        """Register every pending spawn whose (accounted) factory latency
        has elapsed, then replay parked overflow onto the new capacity."""
        if not self._pending_spawns:
            return
        still: list[dict] = []
        for item in self._pending_spawns:
            if item["ready_at"] > now:
                still.append(item)
                continue
            name = f"{self.name_prefix}{item['attempt']}"
            try:
                engine = self.engine_factory()
                rep = self.router.add_replica(engine, name=name)
            except Exception as exc:
                self.spawn_failures += 1
                self._backoff_until = now + self.policy.spawn_backoff_s
                _M_EVENTS.inc(direction=UP, reason="spawn_fail")
                JOURNAL.record(
                    "autoscale", "scale_up.spawn_failed",
                    correlation=item["corr"], attempt=item["attempt"],
                    error=f"{type(exc).__name__}: {exc}",
                    backoff_s=self.policy.spawn_backoff_s,
                )
                continue
            placed = self.router._replay_parked()
            JOURNAL.record(
                "autoscale", "scale_up.resumed", correlation=item["corr"],
                replica=rep.name, parked_placed=placed,
            )
        self._pending_spawns = still

    def _scale_down(self, n: int, reason: str, now: float) -> None:
        for _ in range(max(1, n)):
            victim = self._pick_victim()
            if victim is None:
                return
            corr = self._mint_corr()
            JOURNAL.record(
                "autoscale", "scale_down.begin", correlation=corr,
                replica=victim.name, reason=reason,
                resident=victim.resident(),
            )
            # Pre-seeding evac_corr threads the whole drain (suspect →
            # evacuating → drained → restored-on-survivors) under THIS
            # action's correlation id — one id spans the scaling action.
            victim.evac_corr = corr
            moved = self.router.drain(victim.name, reason="scale_down")
            self._last_action_t = now
            self._down_streak = 0
            self.actions += 1
            _M_EVENTS.inc(direction=DOWN, reason=reason)
            JOURNAL.record(
                "autoscale", "scale_down.resumed", correlation=corr,
                replica=victim.name, moved=len(moved),
            )

    def scale_move(self, dst: FleetRouter, reason: str = "rebalance"):
        """Zero-loss pool rebalancing: drain the least-loaded replica out
        of THIS autoscaler's pool (live migration — resident streams
        restore onto its siblings or park), detach it, and merge-restore
        its engine into ``dst`` under one ``scale-<seq>-<n>`` correlation
        spanning begin → drain → resumed.  ``add_replica``'s fresh id
        stride plus the restore-side ``max(next_id, ...)`` clamp keep
        request ids monotonic across the move, so a replica can bounce
        between pools without ever reissuing an id.  Returns the
        correlation id, or None when no replica can leave (pool at
        ``min_replicas`` or nothing admittable)."""
        victim = self._pick_victim()
        if victim is None:
            return None
        corr = self._mint_corr()
        now = self.clock()
        JOURNAL.record(
            "autoscale", "scale_move.begin", correlation=corr,
            replica=victim.name, reason=reason, resident=victim.resident(),
        )
        victim.evac_corr = corr
        self.router.drain(victim.name, reason="scale_move")
        engine = self.router.remove_replica(victim.name)
        name = victim.name
        if any(r.name == name for r in dst.replicas):
            name = f"{name}-m{self._scale_seq}"
        rep = dst.add_replica(engine, name=name)
        placed = dst._replay_parked()
        self._last_action_t = now
        self.actions += 1
        _M_EVENTS.inc(direction=MOVE, reason=reason)
        JOURNAL.record(
            "autoscale", "scale_move.resumed", correlation=corr,
            replica=rep.name, parked_placed=placed,
        )
        return corr

    def _pick_victim(self):
        """Least-loaded ADMITTABLE replica.  SUSPECT/EVACUATING/DRAINED
        replicas are never picked — they are already leaving or being
        healed, and draining them again would double-journal the exit."""
        candidates = self.router.admittable_replicas()
        if len(candidates) <= self.policy.min_replicas:
            return None
        return min(
            candidates,
            key=lambda r: (r.resident(), -r.engine.free_slots(), r.name),
        )

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The /debug/autoscale contract: policy, streaks, pending
        spawns and the latest decision doc."""
        return {
            "router_seq": self.router.seq,
            "ticks": self.ticks,
            "actions": self.actions,
            "spawn_failures": self.spawn_failures,
            "pending_spawns": [
                {"corr": i["corr"], "ready_at": round(i["ready_at"], 3)}
                for i in self._pending_spawns
            ],
            "policy": {
                "min_replicas": self.policy.min_replicas,
                "max_replicas": self.policy.max_replicas,
                "target_util_high": self.policy.target_util_high,
                "target_util_low": self.policy.target_util_low,
                "queue_high": self.policy.queue_high,
                "up_ticks": self.policy.up_ticks,
                "down_ticks": self.policy.down_ticks,
                "cooldown_s": self.policy.cooldown_s,
            },
            "slo": {
                "offered": self._offered,
                "attained": self._attained,
                "attainment": (
                    round(self._attained / self._offered, 6)
                    if self._offered else None
                ),
            },
            "last_decision": dict(self.last_decision),
        }


@dataclass(frozen=True)
class RebalancePolicy:
    """Thresholds for TTFT-stage-driven pool rebalancing."""

    dominance: float = 2.0   # losing-stage mean must exceed the other's by this
    min_samples: int = 8     # per-stage observations before a window can vote
    vote_ticks: int = 3      # consecutive same-direction votes before acting
    cooldown_s: float = 60.0  # min seconds between moves


class PoolRebalancer:
    """Moves replicas between a disaggregated router's pools toward the
    TTFT stage that dominates the breakdown.

    Sense: drain :meth:`DisaggRouter.take_stage_attribution` each tick
    (the per-stage accumulator behind
    ``tpu_disagg_ttft_breakdown_seconds``).  Vote: when the decode-stage
    mean dominates the prefill-stage mean by ``policy.dominance`` (with
    ``min_samples`` observations on each side), the decode pool is
    starved — vote to move a prefill replica over; the mirror-image vote
    moves one back.  Hysteresis (``vote_ticks`` consecutive
    same-direction votes) and ``cooldown_s`` keep a single slow request
    from sloshing replicas.  Act: the donor pool's
    :meth:`FleetAutoscaler.scale_move` — live-drained, zero-loss, one
    correlation id.
    """

    def __init__(
        self,
        disagg,
        prefill_scaler: FleetAutoscaler,
        decode_scaler: FleetAutoscaler,
        policy: RebalancePolicy | None = None,
        clock=None,
        burn_monitor=None,
    ):
        self.disagg = disagg
        self.prefill_scaler = prefill_scaler
        self.decode_scaler = decode_scaler
        self.policy = policy or RebalancePolicy()
        # Optional obs_plane.SloBurnRateMonitor: a live burn alert drops
        # the hysteresis to a single vote — when the budget is burning,
        # the stage imbalance is costing real SLO, so act now.
        self.burn_monitor = burn_monitor
        self.clock = clock or disagg.clock
        self.ticks = 0
        self.moves = 0
        self._streak_dir = ""
        self._streak = 0
        self._last_move_t: float | None = None
        self.last_decision: dict = {}

    def _vote(self, attr: dict) -> str:
        p = self.policy
        pre = attr.get("prefill") or {}
        dec = attr.get("decode") or {}
        if pre.get("n", 0) < p.min_samples or dec.get("n", 0) < p.min_samples:
            return ""
        if dec["mean_s"] > pre["mean_s"] * p.dominance:
            return "to_decode"   # decode starved: donate a prefill replica
        if pre["mean_s"] > dec["mean_s"] * p.dominance:
            return "to_prefill"
        return ""

    def tick(self) -> dict:
        """One control-law evaluation.  Returns the decision doc (also
        kept as ``last_decision`` for /debug surfaces)."""
        self.ticks += 1
        now = self.clock()
        attr = self.disagg.take_stage_attribution()
        vote = self._vote(attr)
        if vote and vote == self._streak_dir:
            self._streak += 1
        elif vote:
            self._streak_dir, self._streak = vote, 1
        else:
            self._streak_dir, self._streak = "", 0
        corr = None
        in_cooldown = (
            self._last_move_t is not None
            and now - self._last_move_t < self.policy.cooldown_s
        )
        burn_alert = (
            self.burn_monitor is not None and self.burn_monitor.alerting
        )
        need_ticks = 1 if burn_alert else self.policy.vote_ticks
        if (
            self._streak >= need_ticks
            and not in_cooldown
        ):
            donor, taker = (
                (self.prefill_scaler, self.decode_scaler)
                if vote == "to_decode"
                else (self.decode_scaler, self.prefill_scaler)
            )
            corr = donor.scale_move(taker.router, reason=f"ttft_{vote}")
            if corr is not None:
                self.moves += 1
                self._last_move_t = now
            self._streak_dir, self._streak = "", 0
        self.last_decision = {
            "vote": vote, "streak": self._streak, "corr": corr,
            "cooldown": in_cooldown, "attribution": attr,
            "burn_alert": burn_alert,
        }
        return self.last_decision

    def stats(self) -> dict:
        return {
            "ticks": self.ticks,
            "moves": self.moves,
            "streak": self._streak,
            "streak_dir": self._streak_dir,
            "last_decision": dict(self.last_decision),
        }


_LIVE_AUTOSCALERS: "weakref.WeakSet[FleetAutoscaler]" = weakref.WeakSet()


def live_autoscalers() -> list[FleetAutoscaler]:
    return sorted(list(_LIVE_AUTOSCALERS), key=lambda a: a.seq)


def debug_autoscale_doc() -> dict:
    """The /debug/autoscale payload: every live autoscaler's control-law
    state and latest decision (the controller counterpart of
    /debug/fleet)."""
    return {"autoscalers": [a.stats() for a in live_autoscalers()]}
