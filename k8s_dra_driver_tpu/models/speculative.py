"""Greedy speculative decoding — serve the exact target output faster.

Speculative decoding splits a decode step into a cheap *draft* proposal and
a batched *verify* pass on the target model: the draft proposes ``gamma``
tokens sequentially, then the target scores the whole window in ONE chunked
forward (seq dim gamma+1 instead of 1 — near-free on the MXU, since the
autoregressive step is HBM-bound and the chunk re-reads the same weights).
Accepted draft tokens advance the stream several positions per target pass;
under greedy (temperature 0) verification the output is BIT-IDENTICAL to
plain greedy decode on the target — speculation changes latency, never
content.

The TPU-native draft configuration is *int8 self-speculation*: the draft is
the target's own weight-only int8 quantization (`models/quant.py`). No
second model to train or ship, the draft shares the target's distribution
(high acceptance once the model is confident), and the int8 weights halve
the HBM bytes per draft step — the bandwidth that bounds decode.  Any
smaller model with the same vocab (e.g. fewer layers) also works as the
draft.

TPU-idiomatic structure: static shapes everywhere (token buffer sized
``prompt + steps + gamma``, caches at the same cap, scatter writes with
``mode="drop"`` for the tail), the accept/advance loop is one
``lax.while_loop`` whose body does a fixed-shape draft scan + one verify
chunk, and per-row progress is data (a ``pos`` vector), not control flow.

Reference parity note: the reference driver has no ML data plane (it binds
devices for CUDA pods — SURVEY.md §2.11); this module is consumer-side
capability of the TPU framework, exercised on claimed slices.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from k8s_dra_driver_tpu.models.burnin import ModelConfig
from k8s_dra_driver_tpu.models.decode import (
    KVCache,
    decode_chunk,
    decode_step,
    prefill,
)


def accept_advance(proposed, target, active):
    """THE speculative acceptance rule, shared by `speculative_decode` and
    the serving engine (`serve._spec_round`) — one implementation or their
    bit-equality contracts drift.  ``proposed`` [B, gamma] draft tokens,
    ``target`` [B, >= gamma] verifier argmaxes, ``active`` [B] bool.
    Returns (n_acc leading agreements, advance = n_acc + 1 per active row
    — full acceptance commits the gamma+1 bonus token)."""
    gamma = proposed.shape[1]
    matches = (proposed == target[:, :gamma]).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)
    return n_acc, jnp.where(active, n_acc + 1, 0)


class SpecStats(NamedTuple):
    """Speculation telemetry.  ``drafted``/``accepted``/``emitted`` are
    summed over the whole batch; ``rounds`` is loop iterations (shared by
    all rows), so ``tokens_per_round`` is a batch-wide rate."""

    rounds: jax.Array          # while-loop iterations executed
    drafted: jax.Array         # draft tokens proposed, summed over rows
    accepted: jax.Array        # draft tokens accepted, summed over rows
    emitted: jax.Array         # tokens emitted (accepted + corrections), summed

    @property
    def acceptance(self):
        return self.accepted / jnp.maximum(self.drafted, 1)

    @property
    def tokens_per_round(self):
        return self.emitted / jnp.maximum(self.rounds, 1)


def speculative_decode(
    params,
    draft_params,
    prompt: jax.Array,
    steps: int,
    cfg: ModelConfig,
    *,
    gamma: int = 4,
    cache_dtype=jnp.float32,
    return_stats: bool = False,
):
    """Greedy continuation via draft-then-verify: prompt [B, P] -> [B, P+steps].

    Guarantee: identical to ``decode.greedy_decode(params, prompt, steps,
    batch_prefill=True)`` token for token — acceptance only moves the
    speed.  ``draft_params`` may be any weight set with the same vocab and
    layer layout (int8 `quant.quantize_blocks(params)` is the self-draft;
    a shallower model works too — the draft's cache is sized by its own
    block count).

    Per while-loop round, for every unfinished row: the draft proposes
    ``gamma`` tokens with sequential int8-cheap steps (plus one cache-priming
    step on the last proposal, so the draft cache covers the bonus position);
    the target scores the window ``[last_committed, g_1..g_gamma]`` in one
    `decode_chunk`; the row advances by (leading agreements) + 1 — up to
    ``gamma + 1`` on full acceptance, the standard bonus token — writing the
    target's own argmaxes (accepted drafts ARE the target argmaxes, and the
    final position gets the correction/bonus token for free).  Rejected-suffix
    cache entries go stale in place — every consumer masks keys by position
    and both models re-feed from the committed frontier, so stale slots are
    always overwritten before they are first attended.
    """
    b, p_len = prompt.shape
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    total = p_len + steps
    cap = total + gamma  # verify-window slack past the last emitted position
    if cap > cfg.max_seq:
        raise ValueError(
            f"prompt {p_len} + steps {steps} + gamma {gamma} = {cap} exceeds "
            f"max_seq {cfg.max_seq} (speculation needs gamma slack)"
        )

    n_draft_layers = len(draft_params["blocks"])
    rows = jnp.arange(b)
    step_idx = jnp.arange(gamma + 1, dtype=jnp.int32)

    # Prefill both models on the prompt; commit the target's first token.
    t_cache, t_logits = prefill(params, prompt, cfg, max_seq=cap, cache_dtype=cache_dtype)
    d_cache, _ = prefill(draft_params, prompt, cfg, max_seq=cap, cache_dtype=cache_dtype)
    d_cache = KVCache(k=d_cache.k[:n_draft_layers], v=d_cache.v[:n_draft_layers])
    first = jnp.argmax(t_logits, axis=-1).astype(prompt.dtype)
    tokens = jnp.zeros((b, cap), prompt.dtype)
    tokens = tokens.at[:, :p_len].set(prompt).at[:, p_len].set(first)
    # Invariant at loop top: tokens[:, :pos[r]+1] committed for row r; both
    # caches filled through pos[r]-1; tokens[pos[r]] not yet fed to either.
    pos0 = jnp.full((b,), p_len, jnp.int32)

    draft_step = functools.partial(decode_step, cfg=cfg)

    def draft_round(d_cache, tokens, pos, active):
        """gamma sequential draft steps from each row's frontier, plus one
        cache-priming step: iteration ``gamma`` feeds the last proposal
        (position pos+gamma) so its draft-cache key exists and full
        acceptance can commit the gamma+1 bonus token — without it the next
        round's draft would attend a never-written key slot."""

        def body(carry, i):
            cache, toks = carry
            p = pos + i  # [B] absolute position of the token being fed
            tok_in = toks[rows, jnp.minimum(p, cap - 1)]
            logits, cache = draft_step(
                draft_params, cache, tok_in, jnp.minimum(p, cap - 1), active=active
            )
            nxt = jnp.argmax(logits, axis=-1).astype(toks.dtype)
            toks = toks.at[rows, jnp.minimum(p + 1, cap - 1)].set(
                jnp.where(active, nxt, toks[rows, jnp.minimum(p + 1, cap - 1)])
            )
            return (cache, toks), nxt

        (cache, toks), proposed = jax.lax.scan(
            body, (d_cache, tokens), jnp.arange(gamma + 1, dtype=jnp.int32)
        )
        return cache, toks, proposed.T[:, :gamma]  # proposed: [B, gamma]

    def cond(carry):
        _, _, _, pos, _ = carry
        return jnp.any(pos < total)

    def body(carry):
        t_cache, d_cache, tokens, pos, stats = carry
        active = pos < total
        d_cache, tokens, proposed = draft_round(d_cache, tokens, pos, active)

        # Target verify: one chunk over [committed frontier, g_1..g_gamma].
        window_pos = jnp.minimum(pos[:, None] + step_idx[None, :], cap - 1)
        window = tokens[rows[:, None], window_pos]
        logits, t_cache = decode_chunk(
            params,
            t_cache,
            window,
            jnp.minimum(pos, cap - 1 - gamma),
            cfg=cfg,
            active=active,
        )
        target = jnp.argmax(logits, axis=-1).astype(tokens.dtype)  # [B, gamma+1]

        # Full acceptance commits n_acc + 1 = gamma + 1 (the standard bonus
        # token): the priming step in draft_round fed position pos+gamma, so
        # the draft cache covers every position below the new frontier.  On
        # partial acceptance the +1 is the correction token, whose key the
        # next round's sequential re-feed rewrites before any query sees it.
        n_acc, advance = accept_advance(proposed, target, active)

        # Commit: positions pos+1 .. pos+gamma+1 get the target argmaxes
        # (prefix = accepted drafts, then the correction token; the rest is
        # scratch that later rounds overwrite before reading).
        write_pos = jnp.where(
            active[:, None], pos[:, None] + 1 + step_idx[None, :], cap
        )
        tokens = tokens.at[rows[:, None], write_pos].set(
            target, mode="drop"
        )
        new_pos = jnp.minimum(pos + advance, total)
        stats = SpecStats(
            rounds=stats.rounds + 1,
            drafted=stats.drafted + jnp.sum(jnp.where(active, gamma, 0)),
            accepted=stats.accepted + jnp.sum(jnp.where(active, n_acc, 0)),
            emitted=stats.emitted + jnp.sum(new_pos - pos),
        )
        return t_cache, d_cache, tokens, new_pos, stats

    zero = jnp.zeros((), jnp.int32)
    stats0 = SpecStats(rounds=zero, drafted=zero, accepted=zero, emitted=zero)
    _, _, tokens, _, stats = jax.lax.while_loop(
        cond, body, (t_cache, d_cache, tokens, pos0, stats0)
    )
    out = tokens[:, :total]
    return (out, stats) if return_stats else out
