"""Structured-parameter allocator — the kube-scheduler's DRA half.

Allocation happens OUTSIDE the reference repo (SURVEY.md §3.5): the upstream
scheduler reads published ResourceSlices, evaluates DeviceClass + per-request
CEL selectors, honors ``matchAttribute`` constraints and capacity non-overlap,
and writes ``claim.Status.Allocation``.  This module re-implements those
semantics so the repo is a *closed loop* — unit/integration tests, the demo
harness and the bench can schedule claims with no cluster.  It also documents
exactly what geometry encoding the driver relies on:

* device filtering: ``device.driver`` must match the DeviceClass driver
  implied by its selectors; every CEL selector must evaluate true (an
  erroring expression is a non-match, CEL-in-k8s semantics);
* per-pool only the highest observed generation is visible;
* a device may be allocated to at most one claim;
* **counter non-overlap**: within one pool, two allocated devices may never
  both carry the same capacity-marker name (``chip%d`` — geometry.py).  This
  is the scheduler-side contract that makes overlapping ICI subslices
  mutually exclusive, the TPU analog of MIG ``memorySlice%d`` capacities;
* ``matchAttribute`` constraints across requests (gpu-test4.yaml:43-45's
  ``parentUUID`` pattern → our ``hostId``/``sliceDomain``);
* allocation is all-or-nothing per claim, via backtracking search.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Optional

from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import (
    AllocationResult,
    Device,
    DeviceAllocationConfiguration,
    DeviceAllocationResult,
    DeviceRequestAllocationResult,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ResourceClaim,
    ResourceClaimConsumerReference,
)
from k8s_dra_driver_tpu.scheduler import cel
from k8s_dra_driver_tpu.scheduler.index import AllocationIndex
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_CEL_MEMO_HITS = REGISTRY.counter(
    "dra_cel_memo_hits_total",
    "Selector verdicts served from the per-candidate memo",
)
_CEL_MEMO_MISSES = REGISTRY.counter(
    "dra_cel_memo_misses_total",
    "Selector verdicts computed and stored in the per-candidate memo",
)
_CEL_EVALS = REGISTRY.counter(
    "dra_cel_evals_total",
    "CEL selector expressions actually evaluated against a device",
)
_GANG_PLANS = REGISTRY.counter(
    "dra_gang_plans_total",
    "Gang allocation attempts, by outcome "
    "(planned | infeasible | committed | unwound)",
)


class AllocationError(Exception):
    pass


class GangConflictError(AllocationError):
    """A gang commit lost an optimistic-concurrency race mid-flight: some
    member's status write failed (stale resourceVersion, injected 409, an
    admission validator rejecting a double-booked device) after zero or
    more siblings had already committed.  The already-committed siblings
    were unwound in reverse order before this was raised, so the store is
    balanced and the whole gang is safe to retry from a fresh refetch.

    ``unwound`` carries the claim names rolled back (commit order), so
    callers can account for the wasted work without string-matching the
    message."""

    def __init__(self, message: str, unwound: tuple[str, ...] = ()):
        super().__init__(message)
        self.unwound = tuple(unwound)


@dataclass(frozen=True)
class _Candidate:
    driver: str
    pool: str
    device: Device
    # Selector-verdict memo, keyed by CEL expression source.  The candidate
    # object itself is the other half of the memo key: the allocation index
    # rebuilds candidates whenever their slice's resourceVersion (and hence
    # pool generation) changes, so an entry is implicitly scoped to
    # (expression, device, inventory version) and never goes stale.
    verdicts: dict = field(default_factory=dict, compare=False, repr=False)

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.driver, self.pool, self.device.name)

    @functools.cached_property
    def env(self) -> dict:
        """CEL env cached per candidate: capacities parse once, not per
        (request, selector) evaluation on the allocation hot path."""
        return _device_env(self)

    @functools.cached_property
    def markers(self) -> frozenset:
        """This device's (pool, chip-marker) overlap set (geometry.py)."""
        return frozenset(
            (self.pool, cap)
            for cap in self.device.basic.capacity
            if cap.startswith("chip")
        )


def _device_env(c: _Candidate) -> dict:
    """CEL environment for one device, mirroring k8s DRA's `device` variable:
    attributes/capacity are maps keyed by qualified name then attribute.
    Capacities are parsed to integer base units so they compare against
    ``quantity('16Gi')`` (k8s CEL's quantity semantics)."""
    from k8s_dra_driver_tpu.kube import quantity as q

    attrs = cel.AttrBag()
    caps = cel.AttrBag()
    for name, attr in c.device.basic.attributes.items():
        attrs[name] = attr.value
    for name, qty in c.device.basic.capacity.items():
        try:
            caps[name] = q.parse(qty)
        except q.InvalidQuantity:
            caps[name] = qty
    return {
        "device": cel.AttrBag(
            driver=c.driver,
            attributes=cel.AttrBag({c.driver: attrs}),
            capacity=cel.AttrBag({c.driver: caps}),
        )
    }


def _matches_selectors(c: _Candidate, selectors) -> bool:
    for sel in selectors or []:
        if sel.cel is None:
            continue
        expr = sel.cel.expression
        verdict = c.verdicts.get(expr)
        if verdict is None:
            _CEL_MEMO_MISSES.inc()
            _CEL_EVALS.inc()
            try:
                verdict = cel.evaluate(expr, c.env) is True
            except cel.CELError:
                verdict = False  # erroring selector == non-match
            c.verdicts[expr] = verdict
        else:
            _CEL_MEMO_HITS.inc()
        if not verdict:
            return False
    return True


def _qualified_attr(c: _Candidate, qualified_name: str):
    """Resolve a matchAttribute name like 'tpu.google.com/hostId'."""
    if "/" in qualified_name:
        domain, name = qualified_name.rsplit("/", 1)
        if domain != c.driver:
            return None
    else:
        name = qualified_name
    attr = c.device.basic.attributes.get(name)
    return None if attr is None else attr.value


@dataclass(frozen=True)
class Plan:
    """A committed-to-nothing allocation: what `Allocator.plan` chose.

    chosen: ``[(request_name, candidate)]`` for consuming requests;
    admin_results: observer (adminAccess) results placed outside the search;
    free: the node's unallocated candidates at plan time (for scoring);
    classes: DeviceClass index (reused by `allocate` for config gathering).
    """

    chosen: list
    admin_results: list
    free: list
    classes: dict
    used_markers: frozenset
    # Union of the node's visible candidates' markers, precomputed by the
    # allocation index from per-slice marker unions.  Equivalent to the
    # union over ``free``: an allocated device's markers are all in
    # ``used_markers`` (the consumed set records every chip marker of every
    # allocated device), so the difference washes out in tightness().
    node_markers: frozenset = frozenset()

    def tightness(self) -> float:
        """Bin-packing score in [0, 1]: fraction of the node's AVAILABLE
        chip markers this plan consumes (available = markers of free
        devices minus markers other allocations already hold — an
        overlapping subslice device keeps its blocked chips out of the
        denominator).  Higher = tighter fit — a MostAllocated-style signal
        that steers small claims onto already-fragmented nodes so intact
        geometry survives for whole-subslice claims (the same policy
        `_search` applies WITHIN a node, lifted to cross-node choice for
        the extender's prioritize)."""
        if self.node_markers:
            available = set(self.node_markers)
        else:
            # Hand-built Plans (tests, older callers) may not carry the
            # precomputed union; fall back to scanning free candidates.
            available = set()
            for c in self.free:
                available.update(c.markers)  # (pool, marker) pairs
        available -= self.used_markers
        used: set = set()
        for _, c in self.chosen:
            used.update(c.markers)
        if not available:
            return 0.0
        return len(used & available) / len(available)


@dataclass(frozen=True)
class GangMember:
    """One node-claim of a multi-host gang: this claim must land on this
    node, together with every sibling, or not at all."""

    claim: ResourceClaim
    node_name: str
    node_labels: Optional[dict] = None


class Allocator:
    """Allocates pending ResourceClaims against published ResourceSlices.

    Device visibility, the consumed set and the DeviceClass map are read
    through an :class:`AllocationIndex` (scheduler/index.py): plan() cost
    scales with the number of *changed* pools since the last plan, not with
    the total inventory or the number of existing claims.
    """

    # Bound on unwind retries per claim when a gang rolls back under an
    # API fault storm: enough attempts that any limited/sub-certain fault
    # budget converges, small enough that a permanently broken server
    # fails loudly instead of spinning.
    GANG_UNWIND_ATTEMPTS = 100

    def __init__(
        self,
        server: InMemoryAPIServer,
        index: Optional[AllocationIndex] = None,
    ):
        self._server = server
        # N racing schedulers against one in-process store may share one
        # watch-maintained index (each keeps its own Allocator for journal
        # correlation and gang sequencing): in-process watches are delivered
        # synchronously under the store lock, so a private index would be
        # exactly as fresh — the real staleness window is plan()-to-commit
        # in both designs — while costing an extra full inventory replay
        # per scheduler.  The contention harness passes a shared index; a
        # caller-owned index is never closed by this allocator.
        self._index = index if index is not None else AllocationIndex(server)
        self._owns_index = index is None
        self._gang_seq = 0

    def close(self) -> None:
        """Detach the allocation index's watches (long-lived processes that
        create throwaway Allocators against one server should call this).
        A shared index passed into ``__init__`` stays attached — whoever
        built it closes it."""
        if self._owns_index:
            self._index.close()

    def view(self, node_name: str = "", node_labels: Optional[dict] = None):
        """One node's indexed :class:`~k8s_dra_driver_tpu.scheduler.index.PlanView`
        without running a search — the cluster simulator's fragmentation
        probe and debug surfaces read occupancy through this instead of
        groping the private index."""
        labels = dict(node_labels or {})
        labels.setdefault("kubernetes.io/hostname", node_name)
        return self._index.snapshot(node_name, labels)

    # -- public ------------------------------------------------------------

    def allocate(
        self,
        claim: ResourceClaim,
        node_name: str = "",
        node_labels: Optional[dict[str, str]] = None,
    ) -> ResourceClaim:
        """Allocate ``claim`` for a pod placed on ``node_name``.

        Writes ``status.allocation`` back through the API server and returns
        the updated claim.  Raises AllocationError when the claim cannot be
        satisfied on this node.
        """
        if claim.status.allocation is not None:
            return claim  # already allocated (idempotent)
        try:
            p = self.plan(claim, node_name, node_labels)
        except AllocationError as exc:
            JOURNAL.record_lazy(
                "allocator", "allocate.fail", correlation=claim.metadata.uid,
                attrs=lambda: dict(
                    claim=claim.metadata.name, node=node_name, error=str(exc),
                ),
            )
            raise
        return self._commit_plan(claim, node_name, p)

    def _commit_plan(self, claim: ResourceClaim, node_name: str, p: "Plan") -> ResourceClaim:
        """Write one planned allocation through the API server.  On update
        failure the in-memory claim's allocation is reset to None before
        re-raising: faults fire BEFORE the store mutates (utils/faults.py),
        so the store still has no allocation — a retry path that kept the
        local copy's allocation would trip allocate()'s idempotent
        early-return and silently never persist."""
        results = [
            DeviceRequestAllocationResult(
                request=req_name, driver=c.driver, pool=c.pool, device=c.device.name
            )
            for req_name, c in p.chosen
        ] + p.admin_results
        config = self._gather_config(claim, claim.spec.devices.requests, p.classes)
        claim.status.allocation = AllocationResult(
            devices=DeviceAllocationResult(results=results, config=config),
            node_selector=NodeSelector(
                node_selector_terms=[
                    NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(
                                key="kubernetes.io/hostname", values=[node_name]
                            )
                        ]
                    )
                ]
            )
            if node_name
            else None,
        )
        JOURNAL.record_lazy(
            "allocator", "allocate.ok", correlation=claim.metadata.uid,
            attrs=lambda: dict(
                claim=claim.metadata.name, node=node_name,
                devices=[r.device for r in results],
            ),
        )
        try:
            return self._server.update(claim)
        except Exception:
            claim.status.allocation = None
            raise

    def plan(
        self,
        claim: ResourceClaim,
        node_name: str = "",
        node_labels: Optional[dict[str, str]] = None,
        exclude_devices: frozenset = frozenset(),
        extra_markers: frozenset = frozenset(),
    ) -> "Plan":
        """Dry-run feasibility: the FULL allocation search for ``claim`` on
        ``node_name`` — selectors, markers, constraints, backtracking —
        with no write-back.  Raises AllocationError when unsatisfiable.

        ``exclude_devices``/``extra_markers`` thread the chosen devices and
        markers of EARLIER plans into this search, so a multi-claim pod is
        planned jointly (claims planned in isolation would each grab the
        same last chip and pass a node the pod can never bind to).

        This is the scheduler-extender primitive (SURVEY.md §3.5: geometry
        must be CEL/capacity-expressible *unless we also ship a scheduler
        extender*): `filter` calls it per node, `prioritize` scores its
        result, `allocate` commits it.
        """
        node_labels = dict(node_labels or {})
        node_labels.setdefault("kubernetes.io/hostname", node_name)

        # One locked read against the allocation index: visible candidates
        # (cached per pool generation / slice resourceVersion), the
        # incrementally-maintained consumed set, and the DeviceClass map.
        view = self._index.snapshot(node_name, node_labels)
        candidates = view.candidates
        in_use = view.in_use
        used_markers = view.used_markers
        in_use |= set(exclude_devices)
        used_markers |= set(extra_markers)

        free = [c for c in candidates if c.key not in in_use]

        requests = claim.spec.devices.requests
        if not requests:
            raise AllocationError("claim has no device requests")

        classes = view.classes

        per_request: list[tuple[str, int, list[_Candidate]]] = []
        admin_results: list[DeviceRequestAllocationResult] = []
        for req in requests:
            dc = classes.get(req.device_class_name)
            if dc is None:
                raise AllocationError(f"unknown DeviceClass {req.device_class_name!r}")
            # adminAccess requests (monitoring/diagnostics) see devices
            # REGARDLESS of allocation and consume nothing — upstream DRA
            # semantics for the admin-access feature gate.
            pool = candidates if req.admin_access else free
            matching = [
                c
                for c in pool
                if _matches_selectors(c, dc.spec.selectors)
                and _matches_selectors(c, req.selectors)
            ]
            if req.admin_access:
                count = len(matching) if req.allocation_mode == "All" else (req.count or 1)
                if len(matching) < count or count == 0:
                    # zero-match 'All' is a misconfiguration, same as the
                    # normal path — silence would mask it exactly where
                    # diagnostics claims need loudness.
                    raise AllocationError(
                        f"admin request {req.name!r}: {len(matching)} device(s) match, "
                        f"need {max(count, 1)}"
                    )
                admin_results.extend(
                    DeviceRequestAllocationResult(
                        request=req.name, driver=c.driver, pool=c.pool,
                        device=c.device.name, admin_access=True,
                    )
                    for c in matching[:count]
                )
                continue
            if req.allocation_mode == "All":
                count = len(matching)
                if count == 0:
                    raise AllocationError(f"request {req.name!r}: no devices match")
            else:
                count = req.count or 1
            per_request.append((req.name, count, matching))

        # Constraint scoping vs adminAccess: observers are placed outside the
        # backtracking search, so explicitly constraining one is unsupported
        # (loudly); a default-all constraint scopes to the consuming requests.
        admin_names = {r.name for r in requests if r.admin_access}
        constraints = []
        for con in claim.spec.devices.constraints:
            if not con.match_attribute:
                continue
            if con.requests and set(con.requests) & admin_names:
                raise AllocationError(
                    f"matchAttribute constraint over adminAccess request(s) "
                    f"{sorted(set(con.requests) & admin_names)} is not supported"
                )
            scope = set(con.requests or [r.name for r in requests]) - admin_names
            constraints.append((scope, con.match_attribute))

        chosen = self._search(per_request, constraints, used_markers, free)
        if chosen is None:
            raise AllocationError(
                f"claim {claim.metadata.name!r}: cannot satisfy "
                f"{[(name, count) for name, count, _ in per_request]} on node {node_name!r}"
            )
        return Plan(
            chosen=chosen,
            admin_results=admin_results,
            free=free,
            classes=classes,
            used_markers=frozenset(used_markers),
            node_markers=view.node_markers,
        )

    # -- gang allocation (multi-host slices, all-or-nothing) ----------------

    def plan_gang(self, members: list) -> list:
        """Plan a multi-host gang JOINTLY: each :class:`GangMember`'s claim
        is planned on its node with every EARLIER member's chosen devices
        and markers excluded (the `_joint_plans` discipline lifted across
        nodes — device keys and markers are pool-scoped, so the union is
        safe cross-node).  Returns ``[(member, Plan)]`` in member order, or
        raises AllocationError if ANY member is infeasible — nothing was
        committed, so there is nothing to undo (Flex-MIG's gang-execution
        framing: the slice runs whole or not at all)."""
        if not members:
            raise AllocationError("empty gang")
        plans: list = []
        taken_keys: set = set()
        taken_markers: set = set()
        for m in members:
            try:
                p = self.plan(
                    m.claim,
                    node_name=m.node_name,
                    node_labels=m.node_labels,
                    exclude_devices=frozenset(taken_keys),
                    extra_markers=frozenset(taken_markers),
                )
            except AllocationError:
                _GANG_PLANS.inc(outcome="infeasible")
                raise
            for _, c in p.chosen:
                taken_keys.add(c.key)
                taken_markers.update(c.markers)
            plans.append((m, p))
        _GANG_PLANS.inc(outcome="planned")
        return plans

    def allocate_gang(self, members: list) -> list:
        """Commit a gang atomically: plan every member first (a single
        infeasible member aborts before ANY write), then commit member by
        member; a failed commit unwinds every already-committed sibling in
        reverse before raising.  Returns the updated claims in member
        order.  One journal correlation (``gang-<n>``) spans the whole
        attempt — begin, every commit, any unwind."""
        self._gang_seq += 1
        corr = f"gang-{self._gang_seq}"
        plans = self.plan_gang(members)  # raises (and counts) if infeasible
        JOURNAL.record_lazy(
            "allocator", "gang.begin", correlation=corr,
            attrs=lambda: dict(
                members=[
                    (m.claim.metadata.name, m.node_name) for m, _ in plans
                ],
            ),
        )
        committed: list = []
        out: list = []
        for m, p in plans:
            try:
                updated = self._commit_plan(m.claim, m.node_name, p)
            except Exception as exc:  # noqa: BLE001 - any failed write unwinds
                JOURNAL.record(
                    "allocator", "gang.commit_failed", correlation=corr,
                    claim=m.claim.metadata.name, node=m.node_name,
                    error=f"{type(exc).__name__}: {exc}",
                )
                unwound_names = tuple(c.metadata.name for c in committed)
                self._unwind_gang(corr, committed)
                _GANG_PLANS.inc(outcome="unwound")
                raise GangConflictError(
                    f"gang commit failed at {m.claim.metadata.name!r} on "
                    f"{m.node_name!r} ({type(exc).__name__}: {exc}); "
                    f"{len(committed)} sibling(s) unwound",
                    unwound=unwound_names,
                ) from exc
            committed.append(updated)
            out.append(updated)
        _GANG_PLANS.inc(outcome="committed")
        JOURNAL.record(
            "allocator", "gang.committed", correlation=corr,
            members=len(out),
        )
        return out

    def _unwind_gang(self, corr: str, committed: list) -> None:
        """Roll back committed gang members in reverse, retrying each
        deallocation under whatever fault storm broke the commit.  Every
        attempt REFETCHES the claim: the store deep-copies on update, so
        retrying with the stale in-memory object after an injected
        conflict would fight resourceVersions forever."""
        for claim in reversed(committed):
            name = claim.metadata.name
            namespace = claim.metadata.namespace
            last: Exception | None = None
            for _ in range(self.GANG_UNWIND_ATTEMPTS):
                try:
                    current = self._server.get(ResourceClaim.KIND, name, namespace)
                    if current.status.allocation is None:
                        last = None
                        break
                    self.deallocate(current)
                    last = None
                    break
                except Exception as exc:  # noqa: BLE001 - retry under storm
                    last = exc
            if last is not None:
                # Leaked reservation: loud, journaled, never silent.
                JOURNAL.record(
                    "allocator", "gang.unwind_leak", correlation=corr,
                    claim=name,
                    error=f"{type(last).__name__}: {last}",
                )
                raise AllocationError(
                    f"gang unwind could not deallocate {name!r} after "
                    f"{self.GANG_UNWIND_ATTEMPTS} attempts: {last}"
                ) from last
            JOURNAL.record(
                "allocator", "gang.unwound", correlation=corr, claim=name,
            )

    def deallocate(self, claim: ResourceClaim) -> ResourceClaim:
        if claim.status.reserved_for:
            raise AllocationError(
                f"claim {claim.metadata.name!r} still reserved by "
                f"{[r.name for r in claim.status.reserved_for]}"
            )
        claim.status.allocation = None
        JOURNAL.record(
            "allocator", "deallocate", correlation=claim.metadata.uid,
            claim=claim.metadata.name,
        )
        return self._server.update(claim)

    # -- consumer reservation (resource-claim controller semantics) --------

    RESERVED_FOR_LIMIT = 32  # upstream ResourceClaimReservedForMaxSize

    def reserve(self, claim: ResourceClaim, pod_name: str, pod_uid: str) -> ResourceClaim:
        """Record a pod as consumer (claim.status.reservedFor); shared claims
        (gpu-test3 pattern) carry every consuming pod, capped at 32."""
        if any(r.uid == pod_uid for r in claim.status.reserved_for):
            return claim
        if len(claim.status.reserved_for) >= self.RESERVED_FOR_LIMIT:
            raise AllocationError(
                f"claim {claim.metadata.name!r} already reserved by "
                f"{self.RESERVED_FOR_LIMIT} consumers"
            )
        claim.status.reserved_for.append(
            ResourceClaimConsumerReference(resource="pods", name=pod_name, uid=pod_uid)
        )
        JOURNAL.record(
            "allocator", "reserve", correlation=claim.metadata.uid,
            claim=claim.metadata.name, pod=pod_name,
        )
        return self._server.update(claim)

    def unreserve(self, claim: ResourceClaim, pod_uid: str) -> ResourceClaim:
        claim.status.reserved_for = [
            r for r in claim.status.reserved_for if r.uid != pod_uid
        ]
        return self._server.update(claim)

    # -- internals ---------------------------------------------------------

    def _search(self, per_request, constraints, used_markers, free):
        """Backtracking all-or-nothing assignment honoring markers +
        matchAttribute constraints, with BEST-FIT candidate ordering.

        The upstream scheduler allocates first-feasible; we additionally
        score candidates so placements fragment the geometry as little as
        possible (the bin-packing concern MIG operators handle by hand):

        1. fewer chips first — a selector matching several subslice shapes
           takes the smallest that satisfies it;
        2. lower overlap degree first — prefer devices whose allocation
           invalidates the fewest still-allocatable devices, so single-chip
           claims land in already-broken regions and intact blocks survive
           for whole-subslice claims;
        3. device name last, for determinism.
        """
        flat: list[tuple[str, list[_Candidate]]] = []
        for name, count, matching in per_request:
            if len(matching) < count:
                return None
            for _ in range(count):
                flat.append((name, matching))

        chosen: list[tuple[str, _Candidate]] = []
        taken: set = set()
        markers: set = set(used_markers)
        # Constraints are independent of one another even when they name the
        # same attribute: agreement is tracked per constraint *instance*.
        attr_value: dict[int, object] = {}

        def order(matching: list[_Candidate]) -> list[_Candidate]:
            def degree(c: _Candidate) -> int:
                if not c.markers:
                    return 0
                return sum(
                    1
                    for o in free
                    if o.key != c.key
                    and o.key not in taken
                    and o.markers
                    and not (o.markers & markers)  # already infeasible: no loss
                    and (o.markers & c.markers)
                )

            return sorted(
                matching, key=lambda c: (len(c.markers), degree(c), c.device.name)
            )

        def constraint_ok(req_name: str, c: _Candidate) -> bool:
            for ci, (req_set, attr) in enumerate(constraints):
                if req_name not in req_set:
                    continue
                value = _qualified_attr(c, attr)
                if value is None:
                    return False
                if ci in attr_value and attr_value[ci] != value:
                    return False
            return True

        def assign(i: int) -> bool:
            if i == len(flat):
                return True
            req_name, matching = flat[i]
            for c in order(matching):
                if c.key in taken:
                    continue
                # hbm is a real quantity, not an exclusion marker; only the
                # synthetic markers participate in overlap exclusion.
                dev_markers = c.markers
                if dev_markers & markers:
                    continue
                if not constraint_ok(req_name, c):
                    continue
                saved_attrs = dict(attr_value)
                for ci, (req_set, attr) in enumerate(constraints):
                    if req_name in req_set and ci not in attr_value:
                        attr_value[ci] = _qualified_attr(c, attr)
                taken.add(c.key)
                markers.update(dev_markers)
                chosen.append((req_name, c))
                if assign(i + 1):
                    return True
                chosen.pop()
                markers.difference_update(dev_markers)
                taken.discard(c.key)
                attr_value.clear()
                attr_value.update(saved_attrs)
            return False

        return chosen if assign(0) else None

    def _gather_config(self, claim, requests, classes) -> list[DeviceAllocationConfiguration]:
        """Copy class + claim opaque configs into the allocation result with
        their source recorded — the plugin's precedence resolution depends on
        it (device_state.go:225-259: class < claim)."""
        out = []
        for req in requests:
            dc = classes.get(req.device_class_name)
            for cc in dc.spec.config or []:
                if cc.opaque is not None:
                    out.append(
                        DeviceAllocationConfiguration(
                            source="FromClass", requests=[req.name], opaque=cc.opaque
                        )
                    )
        for cc in claim.spec.devices.config or []:
            if cc.opaque is not None:
                out.append(
                    DeviceAllocationConfiguration(
                        source="FromClaim", requests=list(cc.requests), opaque=cc.opaque
                    )
                )
        return out
