"""Multi-objective plan scoring — allocation *quality* as a first-class
policy surface.

``Plan.tightness()`` (scheduler/allocator.py) is a single MostAllocated
scalar: fraction of the node's available chip markers the plan consumes.
It packs well in the small but is blind to everything operators of
partitioned accelerators actually tune for: whether the geometry LEFT
BEHIND is still usable (arxiv 2502.01909's MIG VM-placement framework —
fragmentation of remaining placements, not just fill), what the placement
costs in watts (arxiv 2501.17752: multi-instance power partitioning shows
per-slice power is a schedulable quantity), and whether the largest slice
shapes survive (stranding risk).  This module lifts the scalar into a
weighted :class:`PlanScore` over five objectives, each in ``[0, 1]``
(higher is better), composable by the extender's ``/prioritize``, the
cluster simulator, and ``bench.py plan_scale``:

* **packing** — ``Plan.tightness()`` unchanged: MostAllocated fill of the
  node's available markers.
* **fragmentation** — fraction of the node's REMAINING free chips still
  coverable by an intact multi-chip subslice after this plan commits.
  1.0 means the leftover geometry is whole; 0.0 means the plan shatters
  every surviving block (2502.01909's "remaining placement count"
  objective mapped onto ICI markers).
* **stranding** — shape-aware best fit: the ratio of the node's largest
  intact (fully-free) device before vs after the plan commits.
  Distinguishes "this placement halves the biggest shape the node can
  still serve" from "it only consumed slivers" — the risk that big-slice
  claims starve even though total free capacity looks healthy.
* **power** — normalized watts-per-chip of the chosen devices against the
  per-shape watt table (larger slices amortize controller/interconnect
  power, so filling one 2x4 beats scattering eight singles).  The table
  ships with the topology daemon's info doc (``TPU_POWER_TABLE`` →
  ``power``) or defaults to :data:`DEFAULT_POWER_TABLE`.
* **spread** — LeastAllocated counterweight: fraction of the node's
  available markers the plan leaves free.  A nonzero weight here lets
  operators dial in utilization-balancing instead of pure bin packing.

Weights come from the caller or the ``DRA_SCORE_WEIGHTS`` env var
(``packing=0.4,fragmentation=0.4,power=0.2``), parsed LOUDLY — unknown
objective names, negative/non-finite values and an all-zero vector raise
``ValueError`` (the ``FaultInjector.from_env`` discipline: a typo in a
production knob must never silently fall back to defaults).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

from k8s_dra_driver_tpu.utils.metrics import REGISTRY

ENV_WEIGHTS = "DRA_SCORE_WEIGHTS"

# Packing stays DOMINANT; geometry objectives act inside its quantization
# bins.  The extender wire has 11 score levels, so with packing at 0.75 a
# full stranding swing (1 -> 0) moves the total ~1.5 bins — geometry flips
# a choice only between nodes packing ranks (nearly) equal.  Tuned on the
# cluster simulator's saturated-churn A/B (bench.py plan_scale): across
# seeds this vector beats single-objective tightness on BOTH packing
# efficiency and fragmentation, where geometry-heavy vectors bought their
# fragmentation wins with packing regressions (they out-vote the
# densification signal and scatter small claims over intact nodes).
# ``spread`` ships at 0: it is the exact complement of packing
# (LeastAllocated), kept as a dial for utilization-balancing operators.
DEFAULT_WEIGHTS: dict[str, float] = {
    "packing": 0.75,
    "fragmentation": 0.07,
    "stranding": 0.15,
    "power": 0.03,
    "spread": 0.0,
}

# The single-objective baseline: exactly the pre-PR-15 tightness() policy,
# used as the A side of bench.py plan_scale's A/B.
TIGHTNESS_WEIGHTS: dict[str, float] = {"packing": 1.0}

# Per-DEVICE watts by chip count (not per chip): one v5e chip draws its
# board share alone; a 2x4 subslice amortizes host/ICI overhead across 8
# chips.  Derived from the public v5e ~300W/chip envelope with a modest
# amortization slope — a placeholder the topology daemon's TPU_POWER_TABLE
# overrides with fleet-measured numbers.
DEFAULT_POWER_TABLE: dict[int, float] = {
    1: 310.0,
    2: 600.0,
    4: 1160.0,
    8: 2240.0,
}

_PLAN_SCORE = REGISTRY.gauge(
    "dra_plan_score",
    "Latest multi-objective plan score components (and 'total'), by objective",
)


def parse_weights(raw: str | None) -> dict[str, float]:
    """Parse a ``name=float,name=float`` weight spec (the
    ``DRA_SCORE_WEIGHTS`` wire format).  ``None``/empty returns a copy of
    :data:`DEFAULT_WEIGHTS`.  A provided spec REPLACES the vector:
    objectives not named weigh zero (so ``packing=1`` expresses the
    single-objective baseline).  Unknown names, negative or non-finite
    values, and an all-zero vector raise ``ValueError`` — loud, like
    ``FaultInjector.from_env``."""
    if not raw or not raw.strip():
        return dict(DEFAULT_WEIGHTS)
    out = {name: 0.0 for name in DEFAULT_WEIGHTS}
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"{ENV_WEIGHTS}: malformed entry {part!r} (want name=float)"
            )
        name, _, val = part.partition("=")
        name = name.strip()
        if name not in DEFAULT_WEIGHTS:
            raise ValueError(
                f"{ENV_WEIGHTS}: unknown objective {name!r} "
                f"(have {sorted(DEFAULT_WEIGHTS)})"
            )
        try:
            w = float(val)
        except ValueError as exc:
            raise ValueError(
                f"{ENV_WEIGHTS}: objective {name!r} has non-numeric "
                f"weight {val!r}"
            ) from exc
        if not math.isfinite(w) or w < 0.0:
            raise ValueError(
                f"{ENV_WEIGHTS}: objective {name!r} weight {w} must be "
                f"finite and >= 0"
            )
        out[name] = w
    if not any(out.values()):
        raise ValueError(f"{ENV_WEIGHTS}: all weights are zero")
    return out


def weights_from_env(environ=os.environ) -> dict[str, float]:
    return parse_weights(environ.get(ENV_WEIGHTS))


def power_table_from_info(info: dict) -> dict[int, float]:
    """Extract the per-shape watt table from a topology daemon info doc
    (``{"power": {"1": 310, "8": 2240}}`` — JSON object keys are strings).
    Missing/empty yields the default table; malformed entries raise."""
    raw = info.get("power") or {}
    if not raw:
        return dict(DEFAULT_POWER_TABLE)
    out: dict[int, float] = {}
    for k, v in raw.items():
        chips = int(k)
        watts = float(v)
        if chips <= 0 or not math.isfinite(watts) or watts <= 0:
            raise ValueError(f"power table entry {k!r}={v!r} is not positive")
        out[chips] = watts
    return out


def watts_for(chip_count: int, table: dict[int, float]) -> float:
    """Per-device watts for a ``chip_count``-chip device.  Exact table hit
    or nearest-key scaling (per-chip watts of the closest entry times the
    count) — a 3-chip shape interpolates rather than KeyErroring."""
    chip_count = max(1, int(chip_count))
    if chip_count in table:
        return table[chip_count]
    if not table:
        return float(chip_count)
    nearest = min(table, key=lambda k: (abs(k - chip_count), k))
    return table[nearest] / nearest * chip_count


@dataclass(frozen=True)
class PlanScore:
    """One plan's scored verdict: per-objective components (each in
    [0, 1]) and the weight vector that combined them."""

    components: dict[str, float] = field(default_factory=dict)
    weights: dict[str, float] = field(default_factory=lambda: dict(DEFAULT_WEIGHTS))

    @property
    def total(self) -> float:
        """Weighted mean over the components, in [0, 1]."""
        num = 0.0
        den = 0.0
        for name, w in self.weights.items():
            if w <= 0.0:
                continue
            num += w * self.components.get(name, 0.0)
            den += w
        return num / den if den else 0.0

    def to_dict(self) -> dict:
        return {
            "total": round(self.total, 6),
            "components": {k: round(v, 6) for k, v in self.components.items()},
            "weights": dict(self.weights),
        }


def _largest_intact(free, consumed: set) -> int:
    """Chip count of the largest free device whose markers are untouched
    by ``consumed`` — the biggest shape still placeable on the node."""
    best = 0
    for c in free:
        m = c.markers
        if len(m) > best and not (m & consumed):
            best = len(m)
    return best


def _intact_markers(free, consumed: set, min_chips: int) -> set:
    """Union of markers of free, un-consumed devices with at least
    ``min_chips`` chip markers — the geometry still whole after
    ``consumed`` commits."""
    alive: set = set()
    for c in free:
        m = c.markers
        if len(m) < min_chips:
            continue
        if m & consumed:
            continue
        alive |= m
    return alive


def score_plan(plan, weights: dict[str, float] | None = None,
               power_table: dict[int, float] | None = None) -> PlanScore:
    """Score one :class:`~k8s_dra_driver_tpu.scheduler.allocator.Plan`.

    Reads only what the plan already carries (chosen/free candidates and
    marker sets) — no index access, no server round trips — so the
    extender can score a fanout of nodes at plan() cost."""
    weights = dict(DEFAULT_WEIGHTS) if weights is None else weights
    table = DEFAULT_POWER_TABLE if power_table is None else power_table

    chosen_markers: set = set()
    for _, c in plan.chosen:
        chosen_markers |= c.markers

    if plan.node_markers:
        available = set(plan.node_markers)
    else:
        available = set()
        for c in plan.free:
            available |= c.markers
    available -= set(plan.used_markers)
    remaining = available - chosen_markers
    consumed_after = set(plan.used_markers) | chosen_markers

    # packing: the original tightness, unchanged.
    packing = plan.tightness()

    # fragmentation: how much of the leftover geometry is still coverable
    # by an intact multi-chip device.  Empty leftovers are perfect (the
    # node is exactly full — nothing got stranded).
    if remaining:
        alive = _intact_markers(plan.free, consumed_after, min_chips=2)
        fragmentation = len(alive & remaining) / len(remaining)
    else:
        fragmentation = 1.0

    # stranding: shape-aware best fit — the ratio of the node's largest
    # INTACT (fully-free) device before vs after this plan commits.  A
    # 1-chip claim dropped on an untouched 8-chip node halves its largest
    # intact shape (0.5); the same claim on a node whose biggest survivor
    # is a stray chip changes nothing (1.0).  This is the term that keeps
    # whole big slices alive for the gang claims that need them.
    before = _largest_intact(plan.free, set(plan.used_markers))
    if before >= 2:
        stranding = _largest_intact(plan.free, consumed_after) / before
    else:
        stranding = 1.0  # nothing shaped left to preserve

    # power: mean per-chip watts of the chosen devices, normalized to the
    # table's [min, max] per-chip band.  No consuming choices (admin-only
    # plans) and flat tables score neutral 1.0.
    per_chip = [watts_for(k, table) / k for k in table] or [1.0]
    lo, hi = min(per_chip), max(per_chip)
    chosen_counts = [max(1, len(c.markers)) for _, c in plan.chosen]
    if chosen_counts and hi > lo:
        mean = sum(
            watts_for(k, table) / k for k in chosen_counts
        ) / len(chosen_counts)
        power = 1.0 - min(1.0, max(0.0, (mean - lo) / (hi - lo)))
    else:
        power = 1.0

    # spread: LeastAllocated counterweight (how much headroom survives).
    spread = len(remaining) / len(available) if available else 0.0

    components = {
        "packing": packing,
        "fragmentation": fragmentation,
        "stranding": stranding,
        "power": power,
        "spread": spread,
    }
    score = PlanScore(components=components, weights=weights)
    for name, value in components.items():
        _PLAN_SCORE.set(value, objective=name)
    _PLAN_SCORE.set(score.total, objective="total")
    return score


def shuffle_equal_scores(ranked: list, rng) -> list:
    """Conflict-aware candidate shuffling: permute *within* equal-score runs.

    The extender wire quantizes plan scores to an integer 0..10 band, so at
    cluster scale many candidates tie — and a deterministic ``(-score,
    name)`` sort makes every scheduler chase the same pool, turning ties
    into optimistic-concurrency conflicts when N schedulers race one store.
    Given a list already sorted best-first whose first tuple element is the
    (quantized) score, this reshuffles each maximal run of equal scores
    with the caller's seeded ``rng`` and returns a new list.  Score order
    across runs is untouched: a strictly better candidate is still tried
    first; only the arbitrary tie-break stops being globally synchronized.
    Each scheduler seeds its own rng, so the permutations decorrelate."""
    out: list = []
    i = 0
    while i < len(ranked):
        j = i
        while j < len(ranked) and ranked[j][0] == ranked[i][0]:
            j += 1
        run = list(ranked[i:j])
        if len(run) > 1:
            rng.shuffle(run)
        out.extend(run)
        i = j
    return out
