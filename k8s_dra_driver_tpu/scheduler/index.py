"""Versioned allocation index: the scheduler hot path's amortization layer.

``Allocator.plan()`` used to redo all of its work per allocation: re-list
every ResourceSlice, rebuild every ``_Candidate`` (discarding the
cached-property CEL env and marker frozensets), re-parse every capacity
quantity, and re-scan every ResourceClaim for the consumed set.  At N nodes
x M devices x K claims that is O(N*M + K) per decision — the exact
per-decision cost partition-aware placement work (ParvaGPU, Flex-MIG) shows
must be amortized across an indexed view of device state.

This module is that index.  Three caches, three invalidation keys:

* **pool snapshots** — per (driver, pool): the ``_Candidate`` list of the
  pool's highest generation, grouped per backing slice.  Invalidation key:
  the slice set's (name, resourceVersion) pairs; only pools whose slices
  changed are rebuilt, and unchanged slices inside a rebuilt pool keep
  their candidate objects (and therefore their parsed CEL envs, marker
  frozensets and selector-verdict memos) alive.
* **consumed set** — device keys + (pool, capacity) markers held by
  existing allocations, maintained from per-claim allocation deltas
  instead of a full claim scan.  Invalidation key: a claim's extracted
  result tuple (and any slice change, for marker resolution).
* **DeviceClass map** — by name, maintained from watch events.

Against the in-memory API server the index subscribes informer-style
watches (delivery is synchronous under the server lock, so the index is
never stale within the process).  Against any other client it falls back
to list-and-diff per snapshot: same correctness, still reusing candidates
whose slice resourceVersion is unchanged.

Cache effectiveness is exported through the metrics registry
(``dra_alloc_index_hits_total`` / ``dra_alloc_index_misses_total``) so
tools/perf_smoke.py can prove selector evaluations stay O(changed pools).
"""

from __future__ import annotations

import threading
import zlib
from dataclasses import dataclass, field

from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import DeviceClass, ResourceClaim, ResourceSlice
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_INDEX_HITS = REGISTRY.counter(
    "dra_alloc_index_hits_total",
    "Pool snapshots served from the allocation index without a rebuild",
)
_INDEX_MISSES = REGISTRY.counter(
    "dra_alloc_index_misses_total",
    "Pool snapshots (re)built by the allocation index",
)


@dataclass(eq=False)  # identity semantics: groups live in per-node index lists
class _SliceGroup:
    """Candidates of ONE backing ResourceSlice, plus its node scoping."""

    name: str
    resource_version: str
    node_name: str
    node_selector: object
    candidates: list
    marker_union: frozenset


@dataclass
class _PoolSnapshot:
    generation: int
    groups: list[_SliceGroup] = field(default_factory=list)


@dataclass
class PlanView:
    """Everything one ``plan()`` call needs, read under a single lock."""

    candidates: list
    node_markers: frozenset  # union of visible candidates' chip markers
    in_use: set
    used_markers: set
    classes: dict


class AllocationIndex:
    def __init__(self, server, live: bool | None = None):
        self._server = server
        self._lock = threading.RLock()
        self._slices: dict[str, object] = {}  # slice name -> ResourceSlice
        self._slice_pool: dict[str, tuple[str, str]] = {}  # name -> (driver, pool)
        self._pools: dict[tuple[str, str], _PoolSnapshot] = {}
        self._dirty_pools: set[tuple[str, str]] = set()
        self._classes: dict[str, object] = {}
        # Node-scoped group indexes: snapshot(node) walks only the groups
        # that can possibly be visible to that node, not every pool in the
        # cluster — at 10k pools the all-pools walk IS the plan() cost.
        self._node_groups: dict[str, list] = {}  # node_name -> [_SliceGroup]
        self._global_groups: list = []  # all-nodes / node-selector groups
        # claim uid -> tuple of consuming (driver, pool, device) result keys
        self._claim_alloc: dict[str, tuple] = {}
        self._consumed_dirty = True
        self._in_use: set = set()
        self._used_markers: set = set()
        # Refcounts behind the consumed sets: several claims may pin the
        # same device key or chip marker transiently (unwind races), so
        # set-removal on delta must only fire when the LAST holder leaves.
        self._in_use_refs: dict = {}
        self._marker_refs: dict = {}
        self._device_index: dict | None = None
        self._watches: list = []
        # Live (event-driven) mode requires synchronous in-process watch
        # delivery; any other client gets list-and-diff refresh per plan.
        # ``live=True`` opts a watch-capable client (e.g. RESTClient, whose
        # reflector relists through 410s/ERROR frames) into event-driven
        # mode — the chaos suite uses this to prove index convergence
        # across watch outages.
        self._live = isinstance(server, InMemoryAPIServer) if live is None else live
        if self._live:
            self._watches = [
                server.watch(ResourceSlice.KIND, self._on_slice),
                server.watch(ResourceClaim.KIND, self._on_claim),
                server.watch(DeviceClass.KIND, self._on_class),
            ]

    def close(self) -> None:
        for w in self._watches:
            w.stop()
        self._watches = []

    # -- plan-time read ------------------------------------------------------

    def snapshot(self, node_name: str, node_labels: dict[str, str]) -> PlanView:
        with self._lock:
            if not self._live:
                self._refresh_from_lists()
            for key in self._dirty_pools:
                self._rebuild_pool(key)
            self._dirty_pools.clear()
            candidates: list = []
            markers: set = set()
            # Only this node's own groups plus the cluster-global ones are
            # consulted — pools pinned to OTHER nodes never enter the walk.
            for g in self._node_groups.get(node_name, ()):
                if g.node_selector is not None and not g.node_selector.matches(
                    node_labels
                ):
                    continue
                _INDEX_HITS.inc()
                candidates.extend(g.candidates)
                markers |= g.marker_union
            for g in self._global_groups:
                if g.node_selector is not None and not g.node_selector.matches(
                    node_labels
                ):
                    continue
                _INDEX_HITS.inc()
                candidates.extend(g.candidates)
                markers |= g.marker_union
            if self._consumed_dirty:
                self._rebuild_consumed()
            return PlanView(
                candidates=candidates,
                node_markers=frozenset(markers),
                in_use=set(self._in_use),
                used_markers=set(self._used_markers),
                classes=dict(self._classes),
            )

    # -- watch-event maintenance (live mode) ---------------------------------

    def _on_slice(self, event) -> None:
        s = event.object
        name = s.metadata.name
        pool_key = (s.spec.driver, s.spec.pool.name)
        with self._lock:
            old_key = self._slice_pool.get(name)
            if event.type == "DELETED":
                self._slices.pop(name, None)
                self._slice_pool.pop(name, None)
            else:
                self._slices[name] = s
                self._slice_pool[name] = pool_key
            if old_key is not None and old_key != pool_key:
                self._dirty_pools.add(old_key)
            self._dirty_pools.add(pool_key)
            self._consumed_dirty = True  # marker resolution may change
            self._device_index = None

    def _on_claim(self, event) -> None:
        c = event.object
        uid = c.metadata.uid
        with self._lock:
            if event.type == "DELETED":
                old = self._claim_alloc.pop(uid, None)
                if old:
                    self._consumed_delta(old, ())
                return
            self._apply_claim(uid, c)

    def _on_class(self, event) -> None:
        dc = event.object
        with self._lock:
            if event.type == "DELETED":
                self._classes.pop(dc.metadata.name, None)
            else:
                self._classes[dc.metadata.name] = dc

    def _apply_claim(self, uid: str, claim) -> None:
        alloc = claim.status.allocation
        results: tuple = ()
        if alloc is not None:
            results = tuple(
                (r.driver, r.pool, r.device)
                for r in alloc.devices.results
                if not r.admin_access  # admin access observes, never consumes
            )
        old = self._claim_alloc.get(uid)
        if results:
            if old != results:
                self._claim_alloc[uid] = results
                self._consumed_delta(old or (), results)
        elif old is not None:
            del self._claim_alloc[uid]
            self._consumed_delta(old, ())

    def _consumed_delta(self, old: tuple, new: tuple) -> None:
        """Apply one claim's allocation change to the consumed sets
        incrementally.  Falls back to marking dirty (full rebuild at next
        snapshot) when a rebuild is already pending or the device index is
        invalidated — deltas against stale refcounts would corrupt them."""
        if self._consumed_dirty or self._device_index is None:
            self._consumed_dirty = True
            return
        for key in old:
            if key not in new:
                self._consumed_ref(key, -1)
        for key in new:
            if key not in old:
                self._consumed_ref(key, +1)

    def _consumed_ref(self, key: tuple, step: int) -> None:
        n = self._in_use_refs.get(key, 0) + step
        if n <= 0:
            self._in_use_refs.pop(key, None)
            self._in_use.discard(key)
        else:
            self._in_use_refs[key] = n
            self._in_use.add(key)
        dev = self._device_index.get(key)
        if dev is None:
            # Allocation names a device we can't resolve (slice churn racing
            # the claim event) — punt to the full rebuild.
            self._consumed_dirty = True
            return
        pool = key[1]
        for cap in dev.basic.capacity:
            if not cap.startswith("chip"):
                continue  # hbm etc. is shared capacity, not an exclusion marker
            m = (pool, cap)
            c = self._marker_refs.get(m, 0) + step
            if c <= 0:
                self._marker_refs.pop(m, None)
                self._used_markers.discard(m)
            else:
                self._marker_refs[m] = c
                self._used_markers.add(m)

    # -- list-and-diff refresh (fallback mode) -------------------------------

    def _refresh_from_lists(self) -> None:
        seen: set[str] = set()
        for s in self._server.list(ResourceSlice.KIND):
            name = s.metadata.name
            seen.add(name)
            prev = self._slices.get(name)
            if (
                prev is not None
                and prev.metadata.resource_version == s.metadata.resource_version
            ):
                continue
            self._on_slice_sync(name, s)
        for name in list(self._slices):
            if name not in seen:
                self._on_slice_sync(name, None)
        claim_uids: set[str] = set()
        for c in self._server.list(ResourceClaim.KIND):
            claim_uids.add(c.metadata.uid)
            self._apply_claim(c.metadata.uid, c)
        for uid in list(self._claim_alloc):
            if uid not in claim_uids:
                old = self._claim_alloc.pop(uid)
                self._consumed_delta(old, ())
        self._classes = {
            dc.metadata.name: dc for dc in self._server.list(DeviceClass.KIND)
        }

    def _on_slice_sync(self, name: str, s) -> None:
        old_key = self._slice_pool.get(name)
        if s is None:
            self._slices.pop(name, None)
            self._slice_pool.pop(name, None)
        else:
            pool_key = (s.spec.driver, s.spec.pool.name)
            self._slices[name] = s
            self._slice_pool[name] = pool_key
            self._dirty_pools.add(pool_key)
        if old_key is not None:
            self._dirty_pools.add(old_key)
        self._consumed_dirty = True
        self._device_index = None

    # -- internals -----------------------------------------------------------

    def _rebuild_pool(self, key: tuple[str, str]) -> None:
        # Import here, not at module top: allocator.py owns _Candidate and
        # imports this module — the one-way dependency keeps both importable.
        from k8s_dra_driver_tpu.scheduler.allocator import _Candidate

        _INDEX_MISSES.inc()
        old = self._pools.get(key)
        old_groups = {g.name: g for g in old.groups} if old else {}
        if old:
            for g in old.groups:
                self._index_remove(g)
        slices = [
            self._slices[n] for n, pk in self._slice_pool.items() if pk == key
        ]
        if not slices:
            self._pools.pop(key, None)
            return
        # Per (driver, pool) only the highest generation is visible.
        gen = max(s.spec.pool.generation for s in slices)
        groups: list[_SliceGroup] = []
        for s in sorted(slices, key=lambda s: s.metadata.name):
            if s.spec.pool.generation != gen:
                continue
            prev = old_groups.get(s.metadata.name)
            if (
                prev is not None
                and prev.resource_version == s.metadata.resource_version
            ):
                groups.append(prev)  # candidates + CEL memos survive
                continue
            cands = [
                _Candidate(driver=s.spec.driver, pool=s.spec.pool.name, device=d)
                for d in s.spec.devices
            ]
            union: frozenset = frozenset()
            for c in cands:
                union |= c.markers
            groups.append(
                _SliceGroup(
                    name=s.metadata.name,
                    resource_version=s.metadata.resource_version,
                    node_name=s.spec.node_name,
                    node_selector=s.spec.node_selector,
                    candidates=cands,
                    marker_union=union,
                )
            )
        for g in groups:
            self._index_add(g)
        self._pools[key] = _PoolSnapshot(generation=gen, groups=groups)

    def _index_add(self, g: _SliceGroup) -> None:
        if g.node_name:
            self._node_groups.setdefault(g.node_name, []).append(g)
        else:
            self._global_groups.append(g)

    def _index_remove(self, g: _SliceGroup) -> None:
        if g.node_name:
            bucket = self._node_groups.get(g.node_name)
            if bucket is None:
                return
            try:
                bucket.remove(g)  # identity match: _SliceGroup is eq=False
            except ValueError:
                pass
            if not bucket:
                del self._node_groups[g.node_name]
        else:
            try:
                self._global_groups.remove(g)
            except ValueError:
                pass

    def _rebuild_consumed(self) -> None:
        if self._device_index is None:
            self._device_index = {
                (s.spec.driver, s.spec.pool.name, d.name): d
                for s in self._slices.values()
                for d in s.spec.devices
            }
        in_use_refs: dict = {}
        marker_refs: dict = {}
        for results in self._claim_alloc.values():
            for driver, pool, device in results:
                key = (driver, pool, device)
                in_use_refs[key] = in_use_refs.get(key, 0) + 1
                dev = self._device_index.get(key)
                if dev is not None:
                    for cap in dev.basic.capacity:
                        # Only chip markers are exclusion state; shared caps
                        # like hbm would mark EVERY device in the pool used.
                        if cap.startswith("chip"):
                            m = (pool, cap)
                            marker_refs[m] = marker_refs.get(m, 0) + 1
        self._in_use_refs = in_use_refs
        self._marker_refs = marker_refs
        self._in_use = set(in_use_refs)
        self._used_markers = set(marker_refs)
        self._consumed_dirty = False


def stable_shard(name: str, n_shards: int) -> int:
    """Deterministic shard id for per-scheduler pool sharding.

    The contention harness partitions both pools (nodes) and work items
    across N racing schedulers: scheduler ``i`` prefers names where
    ``stable_shard(name, N) == i`` and spills over to the rest only when
    its shard can't satisfy.  CRC32 rather than ``hash()`` because Python
    string hashing is salted per process — shards must agree across
    schedulers, runs and (future) subprocess workers."""
    if n_shards <= 1:
        return 0
    return zlib.crc32(name.encode("utf-8")) % n_shards
