"""Seeded synthetic-cluster churn simulator: plan() at production scale.

ROADMAP item 3's complaint is that the allocation path has only ever been
measured on toy inventories — a handful of pools, no churn, no faults.
This module builds a synthetic cluster of **thousands of nodes/pools**
with realistic v5e/v6e slice-shape inventories and drives the REAL
`AllocationIndex` + `Allocator.plan()` through compressed time, the way
``models/workload.py::replay()`` drives the fleet: an event heap of claim
arrivals (exponential interarrivals), binds, and releases (lognormal
lifetimes), with conflict/error storms from `utils/faults.py` armed in
windows mid-run.  Nothing in the hot path is mocked — claims go through
the in-memory API server, allocations through `allocate()`/
`allocate_gang()`, occupancy through the index's watch events.

Every claim is accounted **exactly once**: the simulator keeps its own
ledger (submitted = bound + infeasible + failed; bound = live + released)
and periodically *relists* the server's claims to cross-check the ledger
against the store — the audit that catches a double-bind, a leaked
allocation after a gang unwind, or an index that drifted under a fault
storm.  The run fails loudly on any mismatch.

Measured outputs (`SimReport`):

* plan() latency p50/p90 across every scored candidate node,
* packing efficiency — served chip-seconds / offered chip-seconds (how
  much of the demand the placement policy actually managed to pack),
* fragmentation — mean stranded-free fraction over a seeded node sample:
  free chips no intact multi-chip subslice can cover (the arxiv
  2502.01909 fragmentation measure mapped onto ICI markers),
* gang outcomes (committed / infeasible / unwound) and audit failures.

`bench.py plan_scale` runs this at 1k/10k pools with single-objective
(`TIGHTNESS_WEIGHTS`) vs multi-objective (`DEFAULT_WEIGHTS`) scoring on
identical seeds; `make sim-cluster` wires the chaos suite into tier-1.
"""

from __future__ import annotations

import heapq
import json
import random
import time
from dataclasses import dataclass, field

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import (
    SUBSLICE_CLASS,
    TPU_CLASS,
    install_device_classes,
    simple_claim,
)
from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import (
    BasicDevice,
    Device,
    DeviceAttribute,
    ObjectMeta,
    ResourceClaim,
    ResourcePool,
    ResourceSlice,
    ResourceSliceSpec,
)
from k8s_dra_driver_tpu.plugin.geometry import chip_marker
from k8s_dra_driver_tpu.scheduler import objectives
from k8s_dra_driver_tpu.scheduler.allocator import (
    AllocationError,
    Allocator,
    GangMember,
)
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_SIM_CLAIMS = REGISTRY.counter(
    "dra_sim_claims_total",
    "Simulator claim lifecycle events, by outcome "
    "(bound | infeasible | failed | released | gang_committed | "
    "gang_infeasible | gang_unwound)",
)
_SIM_PACKING = REGISTRY.gauge(
    "dra_sim_packing_efficiency",
    "Simulator packing efficiency: served chip-seconds / offered chip-seconds",
)
_SIM_FRAG = REGISTRY.gauge(
    "dra_sim_fragmentation",
    "Simulator fragmentation: mean stranded-free-chip fraction over sampled nodes",
)
_SIM_AUDIT_FAILURES = REGISTRY.counter(
    "dra_sim_audit_failures_total",
    "Simulator relist audits that found ledger/store disagreement",
)


class SimAccountingError(AssertionError):
    """The relist audit found a claim accounted zero or twice."""


# -- synthetic inventory -----------------------------------------------------

# (kind, generation, 2D chip grid).  The grids mirror the per-host chip
# counts of real v5e/v6e machine types (4- and 8-chip hosts).
NODE_TEMPLATES: tuple = (
    ("v5e-4", "v5e", (2, 2)),
    ("v5e-8", "v5e", (2, 4)),
    ("v6e-8", "v6e", (2, 4)),
)

# Published subslice extents per grid: aligned power-of-two blocks, the
# same inventory discipline as plugin/geometry.enumerate_subslices but
# over the simulator's synthetic 2D grids (no tpuinfo binding).  The
# (1, 1) block is the chip device itself, published separately.
_EXTENTS = (1, 2, 4, 8)


def _node_devices(grid: tuple[int, int], generation: str) -> list[Device]:
    """Per-chip devices plus aligned multi-chip subslice devices for one
    node, sharing ``chip%d`` capacity markers so overlapping shapes can
    never be double-booked (the geometry.py non-overlap invariant)."""
    w, h = grid
    common = {
        "generation": DeviceAttribute.of(generation),
        "healthy": DeviceAttribute.of(True),
    }
    devices: list[Device] = []
    for y in range(h):
        for x in range(w):
            i = x + y * w
            devices.append(
                Device(
                    name=f"chip{i}",
                    basic=BasicDevice(
                        attributes={
                            "type": DeviceAttribute.of("tpu"),
                            "index": DeviceAttribute.of(i),
                            **common,
                        },
                        capacity={"hbm": "16Gi", chip_marker(i): "1"},
                    ),
                )
            )
    for ew in _EXTENTS:
        if ew > w or w % ew:
            continue
        for eh in _EXTENTS:
            if eh > h or h % eh or ew * eh < 2:
                continue
            for oy in range(0, h, eh):
                for ox in range(0, w, ew):
                    members = [
                        (ox + dx) + (oy + dy) * w
                        for dy in range(eh)
                        for dx in range(ew)
                    ]
                    capacity = {"hbm": f"{16 * len(members)}Gi"}
                    for i in members:
                        capacity[chip_marker(i)] = "1"
                    devices.append(
                        Device(
                            name=f"ss-{ew}x{eh}-{ox}-{oy}",
                            basic=BasicDevice(
                                attributes={
                                    "type": DeviceAttribute.of("subslice"),
                                    "shape": DeviceAttribute.of(f"{ew}x{eh}"),
                                    "chipCount": DeviceAttribute.of(len(members)),
                                    **common,
                                },
                                capacity=capacity,
                            ),
                        )
                    )
    return devices


# -- configuration -----------------------------------------------------------

@dataclass
class StormWindow:
    """One fault-storm window: ``profile`` is armed at ``start_s`` of sim
    time and disarmed at ``start_s + duration_s``."""

    start_s: float
    duration_s: float
    profile: FaultProfile


@dataclass
class SimConfig:
    seed: int = 0
    n_nodes: int = 1000
    # Node mix weights over NODE_TEMPLATES, in order.
    node_mix: tuple = (0.35, 0.35, 0.30)
    duration_s: float = 600.0  # simulated seconds of churn
    arrival_rate: float = 2.0  # claims per simulated second
    # Lognormal lifetime of a bound claim (simulated seconds).
    lifetime_mu: float = 4.0
    lifetime_sigma: float = 0.8
    # Claim chip-count mix: (chips, weight).  Large shapes are what make
    # fragmentation a real objective — a cluster of 1-chip claims never
    # strands anything.
    claim_mix: tuple = ((1, 0.40), (2, 0.25), (4, 0.22), (8, 0.13))
    fanout: int = 6  # candidate nodes scored per arrival
    gang_fraction: float = 0.08  # fraction of arrivals that are gangs
    gang_size: int = 3  # node-claims per gang
    weights: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_WEIGHTS)
    )
    power_table: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_POWER_TABLE)
    )
    storms: tuple = ()  # StormWindow list
    audit_interval_s: float = 60.0  # relist / fragmentation sample cadence
    sample_nodes: int = 64  # nodes probed per fragmentation sample
    bind_attempts: int = 200  # API retries per bind/release under storms


def default_storms() -> tuple:
    """The `make sim-cluster` chaos recipe: a 409 storm and an APIError
    burst against claim writes mid-run, both budget-capped so the retry
    paths converge deterministically."""
    return (
        StormWindow(
            start_s=120.0,
            duration_s=90.0,
            profile=FaultProfile(
                name="sim-conflict-storm",
                conflict_rate=0.35,
                verbs=("PUT",),
                kinds=("ResourceClaim",),
                limit=300,
            ),
        ),
        StormWindow(
            start_s=300.0,
            duration_s=60.0,
            profile=FaultProfile(
                name="sim-error-burst",
                error_rate=0.25,
                error_code=500,
                verbs=("PUT", "POST", "DELETE"),
                kinds=("ResourceClaim",),
                limit=200,
            ),
        ),
    )


# -- report ------------------------------------------------------------------

@dataclass
class SimReport:
    n_nodes: int = 0
    seed: int = 0
    duration_s: float = 0.0
    total_chips: int = 0
    submitted: int = 0
    bound: int = 0
    infeasible: int = 0
    failed: int = 0
    released: int = 0
    gangs_submitted: int = 0
    gangs_committed: int = 0
    gangs_infeasible: int = 0
    gangs_unwound: int = 0
    audits: int = 0
    audit_failures: int = 0
    leaked_claims: int = 0
    plan_samples: int = 0
    plan_p50_ms: float = 0.0
    plan_p90_ms: float = 0.0
    packing_efficiency: float = 0.0
    fragmentation: float = 0.0  # mean over samples
    fragmentation_final: float = 0.0
    utilization_mean: float = 0.0
    wall_s: float = 0.0

    def to_json(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True)


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# -- the simulator -----------------------------------------------------------

_ARRIVE, _RELEASE, _AUDIT, _STORM_ON, _STORM_OFF = range(5)


class ClusterSim:
    """One seeded churn run over a synthetic cluster.

    Deterministic by construction: one ``random.Random(seed)`` drives
    arrivals, lifetimes, node sampling and claim shapes; the fault
    injector gets ``seed + 1``.  Two runs with the same config produce
    identical event sequences (the gang-atomicity property tests replay
    runs from their seed)."""

    def __init__(self, config: SimConfig | None = None):
        self.config = config or SimConfig()
        self.rng = random.Random(self.config.seed)
        self.injector = FaultInjector(seed=self.config.seed + 1)
        self.server = InMemoryAPIServer(fault_injector=self.injector)
        install_device_classes(self.server)
        self.nodes: list[tuple[str, dict, int]] = []  # (name, labels, chips)
        self.total_chips = 0
        self.report = SimReport(
            n_nodes=self.config.n_nodes,
            seed=self.config.seed,
            duration_s=self.config.duration_s,
        )
        self._build_cluster()
        self.allocator = Allocator(self.server)
        # Ledger: claim name -> (chips, release_t) while live.
        self._live: dict[str, tuple[int, float]] = {}
        self._claim_seq = 0
        self._plan_ms: list[float] = []
        self._frag_samples: list[float] = []
        self._util_samples: list[float] = []
        self._offered_cs = 0.0
        self._served_cs = 0.0

    # -- inventory ----------------------------------------------------------

    def _build_cluster(self) -> None:
        cfg = self.config
        kinds = list(NODE_TEMPLATES)
        weights = list(cfg.node_mix)
        # Device lists are immutable per template — build each once and
        # share: the server deep-copies on create, so sharing the template
        # is safe and keeps 10k-node startup off the profile.
        cache: dict[str, list[Device]] = {}
        for i in range(cfg.n_nodes):
            kind, generation, grid = self.rng.choices(kinds, weights)[0]
            name = f"node-{i:05d}-{kind}"
            devices = cache.get(kind)
            if devices is None:
                devices = cache[kind] = _node_devices(grid, generation)
            self.server.create(
                ResourceSlice(
                    metadata=ObjectMeta(name=f"{name}-slice"),
                    spec=ResourceSliceSpec(
                        driver=DRIVER_NAME,
                        pool=ResourcePool(name=name, generation=1),
                        node_name=name,
                        devices=devices,
                    ),
                )
            )
            chips = grid[0] * grid[1]
            labels = {"kubernetes.io/hostname": name, "tpu.google.com/kind": kind}
            self.nodes.append((name, labels, chips))
            self.total_chips += chips
        self.report.total_chips = self.total_chips

    # -- claim construction -------------------------------------------------

    def _new_claim(self, chips: int) -> ResourceClaim:
        self._claim_seq += 1
        name = f"sim-claim-{self._claim_seq:06d}"
        if chips <= 1:
            return simple_claim(name, device_class=TPU_CLASS, count=1)
        return simple_claim(
            name,
            device_class=SUBSLICE_CLASS,
            count=1,
            selectors=[
                f"device.attributes['{DRIVER_NAME}'].chipCount == {chips}"
            ],
        )

    # -- fault-tolerant API verbs ------------------------------------------

    def _retry(self, what: str, fn):
        """Retry a store verb through injected Conflicts/APIErrors.  Faults
        fire BEFORE the store mutates (utils/faults.py), so a failed verb
        can always be retried verbatim; profiles are budget-capped, so the
        loop converges.  Exhaustion raises — a silent drop here would be a
        mis-accounted claim."""
        last: Exception | None = None
        for _ in range(self.config.bind_attempts):
            try:
                return fn()
            except AllocationError:
                raise
            except Exception as exc:  # noqa: BLE001 - injected Conflict/APIError
                last = exc
        raise SimAccountingError(f"{what}: retries exhausted: {last}")

    def _bind(self, claim: ResourceClaim, node: str, labels: dict) -> ResourceClaim:
        def attempt():
            # REFETCH each try: a failed update left the local copy's
            # allocation reset, but resourceVersion may have moved.
            current = self._retry(
                "get", lambda: self.server.get(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                )
            )
            return self.allocator.allocate(
                current, node_name=node, node_labels=labels
            )

        return self._retry(f"bind {claim.metadata.name}", attempt)

    def _unbind(self, name: str, namespace: str = "default") -> None:
        def attempt():
            current = self.server.get(ResourceClaim.KIND, name, namespace)
            if current.status.allocation is not None:
                self.allocator.deallocate(current)
            return True

        self._retry(f"release {name}", attempt)
        self._retry(
            f"delete {name}",
            lambda: self.server.delete(ResourceClaim.KIND, name, namespace),
        )

    # -- event handlers -----------------------------------------------------

    def _score_nodes(self, claim: ResourceClaim, candidates: list) -> list:
        """(score, -1*tie, name, labels, plan) per feasible candidate node,
        best first.  Every plan() call is timed — this IS the latency
        sample the report's p50/p90 comes from."""
        scored = []
        for name, labels, _ in candidates:
            t0 = time.perf_counter()
            try:
                plan = self.allocator.plan(claim, node_name=name, node_labels=labels)
            except AllocationError:
                self._plan_ms.append((time.perf_counter() - t0) * 1000.0)
                continue
            self._plan_ms.append((time.perf_counter() - t0) * 1000.0)
            total = objectives.score_plan(
                plan,
                weights=self.config.weights,
                power_table=self.config.power_table,
            ).total
            # Quantize to the extender's 0..10 wire contract: the
            # kube-scheduler never sees the float, so the simulator must
            # not rank on precision the real system cannot express.  The
            # coarse bins also make near-ties collapse onto the name
            # tie-break, the same first-fit concentration the extender's
            # deterministic node ordering produces in a real cluster.
            scored.append((round(10 * total), name, labels, plan))
        scored.sort(key=lambda t: (-t[0], t[1]))
        return scored

    def _arrive(self, now: float) -> None:
        cfg = self.config
        chips = self.rng.choices(
            [c for c, _ in cfg.claim_mix], [w for _, w in cfg.claim_mix]
        )[0]
        lifetime = self.rng.lognormvariate(cfg.lifetime_mu, cfg.lifetime_sigma)
        candidates = self.rng.sample(self.nodes, min(cfg.fanout, len(self.nodes)))
        if cfg.gang_fraction > 0 and self.rng.random() < cfg.gang_fraction:
            self._arrive_gang(now, chips, lifetime, candidates)
            return
        self.report.submitted += 1
        self._offered_cs += chips * lifetime
        claim = self._new_claim(chips)
        claim = self._retry(
            f"create {claim.metadata.name}", lambda: self.server.create(claim)
        )
        scored = self._score_nodes(claim, candidates)
        if not scored:
            self.report.infeasible += 1
            _SIM_CLAIMS.inc(outcome="infeasible")
            self._retry(
                f"delete {claim.metadata.name}",
                lambda: self.server.delete(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                ),
            )
            return
        _, node, labels, _ = scored[0]
        try:
            bound = self._bind(claim, node, labels)
        except AllocationError:
            # Lost a race against a concurrent event between plan and bind
            # (single-threaded here, so this is storm-driven state drift).
            self.report.infeasible += 1
            _SIM_CLAIMS.inc(outcome="infeasible")
            self._retry(
                f"delete {claim.metadata.name}",
                lambda: self.server.delete(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                ),
            )
            return
        self.report.bound += 1
        _SIM_CLAIMS.inc(outcome="bound")
        self._served_cs += chips * lifetime
        self._live[bound.metadata.name] = (chips, now + lifetime)
        heapq.heappush(
            self._events,
            (now + lifetime, self._seq(), _RELEASE, bound.metadata.name),
        )

    def _arrive_gang(self, now: float, chips: int, lifetime: float,
                     candidates: list) -> None:
        cfg = self.config
        self.report.gangs_submitted += 1
        size = min(cfg.gang_size, len(candidates))
        self.report.submitted += size
        self._offered_cs += chips * lifetime * size
        # Rank candidate nodes by a probe member's score, take the top
        # ``size`` distinct nodes as the gang's placement.
        probe = self._new_claim(chips)
        scored = self._score_nodes(probe, candidates)
        self._claim_seq -= 1  # probe claim was never created server-side
        if len(scored) < size:
            self.report.infeasible += size
            self.report.gangs_infeasible += 1
            _SIM_CLAIMS.inc(outcome="gang_infeasible")
            return
        members = []
        for _, node, labels, _ in scored[:size]:
            claim = self._new_claim(chips)
            claim = self._retry(
                f"create {claim.metadata.name}",
                lambda c=claim: self.server.create(c),
            )
            members.append(GangMember(claim=claim, node_name=node, node_labels=labels))
        try:
            committed = self._retry(
                "gang allocate", lambda: self._gang_attempt(members)
            )
        except (AllocationError, SimAccountingError):
            for m in members:
                self._retry(
                    f"delete {m.claim.metadata.name}",
                    lambda mm=m: self.server.delete(
                        ResourceClaim.KIND, mm.claim.metadata.name,
                        mm.claim.metadata.namespace,
                    ),
                )
            self.report.infeasible += size
            self.report.gangs_infeasible += 1
            _SIM_CLAIMS.inc(outcome="gang_infeasible")
            return
        self.report.gangs_committed += 1
        _SIM_CLAIMS.inc(outcome="gang_committed")
        for claim in committed:
            self.report.bound += 1
            _SIM_CLAIMS.inc(outcome="bound")
            self._served_cs += chips * lifetime
            self._live[claim.metadata.name] = (chips, now + lifetime)
            heapq.heappush(
                self._events,
                (now + lifetime, self._seq(), _RELEASE, claim.metadata.name),
            )

    def _gang_attempt(self, members: list) -> list:
        """One allocate_gang try with refetched members — after a storm
        unwind, the claims must be re-read (committed-then-unwound members
        have new resourceVersions and no allocation)."""
        fresh = []
        for m in members:
            current = self.server.get(
                ResourceClaim.KIND, m.claim.metadata.name,
                m.claim.metadata.namespace,
            )
            fresh.append(GangMember(
                claim=current, node_name=m.node_name, node_labels=m.node_labels,
            ))
        try:
            return self.allocator.allocate_gang(fresh)
        except AllocationError as exc:
            # Unwound commits re-raise as AllocationError; distinguish a
            # genuinely infeasible gang (give up) from a storm-broken one
            # (retry) by whether anything was unwound.
            if "unwound" in str(exc):
                self.report.gangs_unwound += 1
                _SIM_CLAIMS.inc(outcome="gang_unwound")
                raise RuntimeError("gang unwound under storm; retry") from exc
            raise

    def _release(self, name: str) -> None:
        self._unbind(name)
        self._live.pop(name, None)
        self.report.released += 1
        _SIM_CLAIMS.inc(outcome="released")

    # -- audits -------------------------------------------------------------

    def _audit(self) -> None:
        """Relist the store and reconcile against the ledger: every claim
        with an allocation must be exactly one live ledger entry and vice
        versa — the exactly-once accounting check."""
        self.report.audits += 1
        allocated = {
            c.metadata.name
            for c in self.server.list(ResourceClaim.KIND)
            if c.status.allocation is not None
        }
        ledger = set(self._live)
        if allocated != ledger:
            self.report.audit_failures += 1
            _SIM_AUDIT_FAILURES.inc()
            JOURNAL.record(
                "cluster_sim", "audit.mismatch",
                store_only=sorted(allocated - ledger)[:5],
                ledger_only=sorted(ledger - allocated)[:5],
            )
        self._sample_fragmentation()

    def _sample_fragmentation(self) -> None:
        """Stranded-free fraction over a seeded node sample: free chips
        that NO intact (fully-free) multi-chip subslice device covers.
        Also samples cluster utilization over the same nodes."""
        sample = self.rng.sample(
            self.nodes, min(self.config.sample_nodes, len(self.nodes))
        )
        stranded_total = 0
        free_total = 0
        chips_total = 0
        for name, labels, chips in sample:
            view = self.allocator.view(name, labels)
            free = set(view.node_markers) - view.used_markers
            chips_total += chips
            if not free:
                continue
            intact: set = set()
            for c in view.candidates:
                m = c.markers
                if len(m) >= 2 and not (m & view.used_markers):
                    intact |= m
            stranded_total += len(free - intact)
            free_total += len(free)
        if free_total:
            frac = stranded_total / free_total
            self._frag_samples.append(frac)
            _SIM_FRAG.set(frac)
        if chips_total:
            used = chips_total - free_total
            self._util_samples.append(used / chips_total)

    # -- main loop ----------------------------------------------------------

    def _seq(self) -> int:
        self._event_seq += 1
        return self._event_seq

    def run(self) -> SimReport:
        cfg = self.config
        wall0 = time.perf_counter()
        self._events: list = []
        self._event_seq = 0
        # Seed the schedule: first arrival, audits, storm windows.
        heapq.heappush(self._events, (0.0, self._seq(), _ARRIVE, None))
        t = cfg.audit_interval_s
        while t < cfg.duration_s:
            heapq.heappush(self._events, (t, self._seq(), _AUDIT, None))
            t += cfg.audit_interval_s
        for storm in cfg.storms:
            heapq.heappush(
                self._events, (storm.start_s, self._seq(), _STORM_ON, storm)
            )
            heapq.heappush(
                self._events,
                (storm.start_s + storm.duration_s, self._seq(), _STORM_OFF, storm),
            )
        JOURNAL.record(
            "cluster_sim", "run.begin", nodes=cfg.n_nodes, seed=cfg.seed,
            duration_s=cfg.duration_s, arrival_rate=cfg.arrival_rate,
        )
        while self._events:
            now, _, kind, payload = heapq.heappop(self._events)
            if kind == _ARRIVE:
                if now < cfg.duration_s:
                    self._arrive(now)
                    gap = self.rng.expovariate(cfg.arrival_rate)
                    heapq.heappush(
                        self._events, (now + gap, self._seq(), _ARRIVE, None)
                    )
            elif kind == _RELEASE:
                self._release(payload)
            elif kind == _AUDIT:
                self._audit()
            elif kind == _STORM_ON:
                self.injector.arm(payload.profile)
            elif kind == _STORM_OFF:
                self.injector.disarm(payload.profile.name)
        # Drain done (RELEASE events past duration_s still ran).  Disarm
        # everything and run the final audit: the cluster must be empty.
        self.injector.disarm()
        self._audit()
        self.report.leaked_claims = len(self._live) + sum(
            1
            for c in self.server.list(ResourceClaim.KIND)
            if c.status.allocation is not None
        )
        self._finalize(wall0)
        JOURNAL.record(
            "cluster_sim", "run.end", bound=self.report.bound,
            released=self.report.released,
            audit_failures=self.report.audit_failures,
            leaked=self.report.leaked_claims,
        )
        return self.report

    def _finalize(self, wall0: float) -> None:
        r = self.report
        r.plan_samples = len(self._plan_ms)
        r.plan_p50_ms = round(_percentile(self._plan_ms, 0.50), 3)
        r.plan_p90_ms = round(_percentile(self._plan_ms, 0.90), 3)
        r.packing_efficiency = round(
            self._served_cs / self._offered_cs if self._offered_cs else 0.0, 4
        )
        r.fragmentation = round(
            sum(self._frag_samples) / len(self._frag_samples)
            if self._frag_samples else 0.0, 4
        )
        r.fragmentation_final = round(
            self._frag_samples[-1] if self._frag_samples else 0.0, 4
        )
        r.utilization_mean = round(
            sum(self._util_samples) / len(self._util_samples)
            if self._util_samples else 0.0, 4
        )
        r.wall_s = round(time.perf_counter() - wall0, 2)
        _SIM_PACKING.set(r.packing_efficiency)

    def close(self) -> None:
        self.allocator.close()


def run_sim(config: SimConfig | None = None) -> SimReport:
    """Build, run, close — the one-call surface bench.py and the chaos
    suite use."""
    sim = ClusterSim(config)
    try:
        return sim.run()
    finally:
        sim.close()
