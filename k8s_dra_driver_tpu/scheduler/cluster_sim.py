"""Seeded synthetic-cluster churn simulator: plan() at production scale.

ROADMAP item 3's complaint is that the allocation path has only ever been
measured on toy inventories — a handful of pools, no churn, no faults.
This module builds a synthetic cluster of **thousands of nodes/pools**
with realistic v5e/v6e slice-shape inventories and drives the REAL
`AllocationIndex` + `Allocator.plan()` through compressed time, the way
``models/workload.py::replay()`` drives the fleet: an event heap of claim
arrivals (exponential interarrivals), binds, and releases (lognormal
lifetimes), with conflict/error storms from `utils/faults.py` armed in
windows mid-run.  Nothing in the hot path is mocked — claims go through
the in-memory API server, allocations through `allocate()`/
`allocate_gang()`, occupancy through the index's watch events.

Every claim is accounted **exactly once**: the simulator keeps its own
ledger (submitted = bound + infeasible + failed; bound = live + released)
and periodically *relists* the server's claims to cross-check the ledger
against the store — the audit that catches a double-bind, a leaked
allocation after a gang unwind, or an index that drifted under a fault
storm.  The run fails loudly on any mismatch.

Measured outputs (`SimReport`):

* plan() latency p50/p90 across every scored candidate node,
* packing efficiency — served chip-seconds / offered chip-seconds (how
  much of the demand the placement policy actually managed to pack),
* fragmentation — mean stranded-free fraction over a seeded node sample:
  free chips no intact multi-chip subslice can cover (the arxiv
  2502.01909 fragmentation measure mapped onto ICI markers),
* gang outcomes (committed / infeasible / unwound) and audit failures.

`bench.py plan_scale` runs this at 1k/10k pools with single-objective
(`TIGHTNESS_WEIGHTS`) vs multi-objective (`DEFAULT_WEIGHTS`) scoring on
identical seeds; `make sim-cluster` wires the chaos suite into tier-1.
"""

from __future__ import annotations

import dataclasses
import heapq
import json
import random
import threading
import time
from dataclasses import dataclass, field

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import (
    SUBSLICE_CLASS,
    TPU_CLASS,
    install_device_classes,
    simple_claim,
)
from k8s_dra_driver_tpu.kube.fakeserver import Conflict, InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import (
    BasicDevice,
    Device,
    DeviceAttribute,
    ObjectMeta,
    ResourceClaim,
    ResourcePool,
    ResourceSlice,
    ResourceSliceSpec,
)
from k8s_dra_driver_tpu.plugin.geometry import chip_marker
from k8s_dra_driver_tpu.scheduler import objectives
from k8s_dra_driver_tpu.scheduler.allocator import (
    AllocationError,
    Allocator,
    GangConflictError,
    GangMember,
)
from k8s_dra_driver_tpu.scheduler.index import AllocationIndex, stable_shard
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from k8s_dra_driver_tpu.utils.retry import Backoff, ContentionBackoff, RetryPolicy

_SIM_CLAIMS = REGISTRY.counter(
    "dra_sim_claims_total",
    "Simulator claim lifecycle events, by outcome "
    "(bound | infeasible | failed | released | gang_committed | "
    "gang_infeasible | gang_unwound)",
)
_SIM_PACKING = REGISTRY.gauge(
    "dra_sim_packing_efficiency",
    "Simulator packing efficiency: served chip-seconds / offered chip-seconds",
)
_SIM_FRAG = REGISTRY.gauge(
    "dra_sim_fragmentation",
    "Simulator fragmentation: mean stranded-free-chip fraction over sampled nodes",
)
_SIM_AUDIT_FAILURES = REGISTRY.counter(
    "dra_sim_audit_failures_total",
    "Simulator relist audits that found ledger/store disagreement",
)


class SimAccountingError(AssertionError):
    """The relist audit found a claim accounted zero or twice."""


# -- synthetic inventory -----------------------------------------------------

# (kind, generation, 2D chip grid).  The grids mirror the per-host chip
# counts of real v5e/v6e machine types (4- and 8-chip hosts).
NODE_TEMPLATES: tuple = (
    ("v5e-4", "v5e", (2, 2)),
    ("v5e-8", "v5e", (2, 4)),
    ("v6e-8", "v6e", (2, 4)),
)

# Published subslice extents per grid: aligned power-of-two blocks, the
# same inventory discipline as plugin/geometry.enumerate_subslices but
# over the simulator's synthetic 2D grids (no tpuinfo binding).  The
# (1, 1) block is the chip device itself, published separately.
_EXTENTS = (1, 2, 4, 8)


def _node_devices(grid: tuple[int, int], generation: str) -> list[Device]:
    """Per-chip devices plus aligned multi-chip subslice devices for one
    node, sharing ``chip%d`` capacity markers so overlapping shapes can
    never be double-booked (the geometry.py non-overlap invariant)."""
    w, h = grid
    common = {
        "generation": DeviceAttribute.of(generation),
        "healthy": DeviceAttribute.of(True),
    }
    devices: list[Device] = []
    for y in range(h):
        for x in range(w):
            i = x + y * w
            devices.append(
                Device(
                    name=f"chip{i}",
                    basic=BasicDevice(
                        attributes={
                            "type": DeviceAttribute.of("tpu"),
                            "index": DeviceAttribute.of(i),
                            **common,
                        },
                        capacity={"hbm": "16Gi", chip_marker(i): "1"},
                    ),
                )
            )
    for ew in _EXTENTS:
        if ew > w or w % ew:
            continue
        for eh in _EXTENTS:
            if eh > h or h % eh or ew * eh < 2:
                continue
            for oy in range(0, h, eh):
                for ox in range(0, w, ew):
                    members = [
                        (ox + dx) + (oy + dy) * w
                        for dy in range(eh)
                        for dx in range(ew)
                    ]
                    capacity = {"hbm": f"{16 * len(members)}Gi"}
                    for i in members:
                        capacity[chip_marker(i)] = "1"
                    devices.append(
                        Device(
                            name=f"ss-{ew}x{eh}-{ox}-{oy}",
                            basic=BasicDevice(
                                attributes={
                                    "type": DeviceAttribute.of("subslice"),
                                    "shape": DeviceAttribute.of(f"{ew}x{eh}"),
                                    "chipCount": DeviceAttribute.of(len(members)),
                                    **common,
                                },
                                capacity=capacity,
                            ),
                        )
                    )
    return devices


def build_synthetic_cluster(
    server: InMemoryAPIServer,
    rng: random.Random,
    n_nodes: int,
    node_mix: tuple,
) -> tuple[list, int]:
    """Publish a seeded synthetic inventory of ``n_nodes`` single-node
    pools (one ResourceSlice each, NODE_TEMPLATES mix) into ``server``;
    returns ``([(name, labels, chips), ...], total_chips)``.  Shared by
    the churn simulator and the multi-scheduler contention harness so
    both measure the same inventory shape.  Device lists are immutable
    per template — each is built once and shared; the server deep-copies
    on create, so sharing keeps 10k-node startup off the profile."""
    kinds = list(NODE_TEMPLATES)
    weights = list(node_mix)
    cache: dict[str, list[Device]] = {}
    nodes: list[tuple[str, dict, int]] = []
    total_chips = 0
    for i in range(n_nodes):
        kind, generation, grid = rng.choices(kinds, weights)[0]
        name = f"node-{i:05d}-{kind}"
        devices = cache.get(kind)
        if devices is None:
            devices = cache[kind] = _node_devices(grid, generation)
        server.create(
            ResourceSlice(
                metadata=ObjectMeta(name=f"{name}-slice"),
                spec=ResourceSliceSpec(
                    driver=DRIVER_NAME,
                    pool=ResourcePool(name=name, generation=1),
                    node_name=name,
                    devices=devices,
                ),
            )
        )
        chips = grid[0] * grid[1]
        labels = {"kubernetes.io/hostname": name, "tpu.google.com/kind": kind}
        nodes.append((name, labels, chips))
        total_chips += chips
    return nodes, total_chips


# -- configuration -----------------------------------------------------------

@dataclass
class StormWindow:
    """One fault-storm window: ``profile`` is armed at ``start_s`` of sim
    time and disarmed at ``start_s + duration_s``."""

    start_s: float
    duration_s: float
    profile: FaultProfile


@dataclass
class SimConfig:
    seed: int = 0
    n_nodes: int = 1000
    # Node mix weights over NODE_TEMPLATES, in order.
    node_mix: tuple = (0.35, 0.35, 0.30)
    duration_s: float = 600.0  # simulated seconds of churn
    arrival_rate: float = 2.0  # claims per simulated second
    # Lognormal lifetime of a bound claim (simulated seconds).
    lifetime_mu: float = 4.0
    lifetime_sigma: float = 0.8
    # Claim chip-count mix: (chips, weight).  Large shapes are what make
    # fragmentation a real objective — a cluster of 1-chip claims never
    # strands anything.
    claim_mix: tuple = ((1, 0.40), (2, 0.25), (4, 0.22), (8, 0.13))
    fanout: int = 6  # candidate nodes scored per arrival
    gang_fraction: float = 0.08  # fraction of arrivals that are gangs
    gang_size: int = 3  # node-claims per gang
    weights: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_WEIGHTS)
    )
    power_table: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_POWER_TABLE)
    )
    storms: tuple = ()  # StormWindow list
    audit_interval_s: float = 60.0  # relist / fragmentation sample cadence
    sample_nodes: int = 64  # nodes probed per fragmentation sample
    bind_attempts: int = 200  # API retries per bind/release under storms


def default_storms() -> tuple:
    """The `make sim-cluster` chaos recipe: a 409 storm and an APIError
    burst against claim writes mid-run, both budget-capped so the retry
    paths converge deterministically."""
    return (
        StormWindow(
            start_s=120.0,
            duration_s=90.0,
            profile=FaultProfile(
                name="sim-conflict-storm",
                conflict_rate=0.35,
                verbs=("PUT",),
                kinds=("ResourceClaim",),
                limit=300,
            ),
        ),
        StormWindow(
            start_s=300.0,
            duration_s=60.0,
            profile=FaultProfile(
                name="sim-error-burst",
                error_rate=0.25,
                error_code=500,
                verbs=("PUT", "POST", "DELETE"),
                kinds=("ResourceClaim",),
                limit=200,
            ),
        ),
    )


# -- report ------------------------------------------------------------------

@dataclass
class SimReport:
    n_nodes: int = 0
    seed: int = 0
    duration_s: float = 0.0
    total_chips: int = 0
    submitted: int = 0
    bound: int = 0
    infeasible: int = 0
    failed: int = 0
    released: int = 0
    gangs_submitted: int = 0
    gangs_committed: int = 0
    gangs_infeasible: int = 0
    gangs_unwound: int = 0
    audits: int = 0
    audit_failures: int = 0
    leaked_claims: int = 0
    plan_samples: int = 0
    plan_p50_ms: float = 0.0
    plan_p90_ms: float = 0.0
    packing_efficiency: float = 0.0
    fragmentation: float = 0.0  # mean over samples
    fragmentation_final: float = 0.0
    utilization_mean: float = 0.0
    wall_s: float = 0.0

    def to_json(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True)


def _percentile(samples: list[float], q: float) -> float:
    if not samples:
        return 0.0
    s = sorted(samples)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


# -- the simulator -----------------------------------------------------------

_ARRIVE, _RELEASE, _AUDIT, _STORM_ON, _STORM_OFF = range(5)


class ClusterSim:
    """One seeded churn run over a synthetic cluster.

    Deterministic by construction: one ``random.Random(seed)`` drives
    arrivals, lifetimes, node sampling and claim shapes; the fault
    injector gets ``seed + 1``.  Two runs with the same config produce
    identical event sequences (the gang-atomicity property tests replay
    runs from their seed)."""

    def __init__(self, config: SimConfig | None = None):
        self.config = config or SimConfig()
        self.rng = random.Random(self.config.seed)
        self.injector = FaultInjector(seed=self.config.seed + 1)
        self.server = InMemoryAPIServer(fault_injector=self.injector)
        install_device_classes(self.server)
        self.nodes: list[tuple[str, dict, int]] = []  # (name, labels, chips)
        self.total_chips = 0
        self.report = SimReport(
            n_nodes=self.config.n_nodes,
            seed=self.config.seed,
            duration_s=self.config.duration_s,
        )
        self._build_cluster()
        self.allocator = Allocator(self.server)
        # Ledger: claim name -> (chips, release_t) while live.
        self._live: dict[str, tuple[int, float]] = {}
        self._claim_seq = 0
        self._plan_ms: list[float] = []
        self._frag_samples: list[float] = []
        self._util_samples: list[float] = []
        self._offered_cs = 0.0
        self._served_cs = 0.0

    # -- inventory ----------------------------------------------------------

    def _build_cluster(self) -> None:
        cfg = self.config
        self.nodes, self.total_chips = build_synthetic_cluster(
            self.server, self.rng, cfg.n_nodes, cfg.node_mix
        )
        self.report.total_chips = self.total_chips

    # -- claim construction -------------------------------------------------

    def _new_claim(self, chips: int) -> ResourceClaim:
        self._claim_seq += 1
        name = f"sim-claim-{self._claim_seq:06d}"
        if chips <= 1:
            return simple_claim(name, device_class=TPU_CLASS, count=1)
        return simple_claim(
            name,
            device_class=SUBSLICE_CLASS,
            count=1,
            selectors=[
                f"device.attributes['{DRIVER_NAME}'].chipCount == {chips}"
            ],
        )

    # -- fault-tolerant API verbs ------------------------------------------

    def _retry(self, what: str, fn):
        """Retry a store verb through injected Conflicts/APIErrors.  Faults
        fire BEFORE the store mutates (utils/faults.py), so a failed verb
        can always be retried verbatim; profiles are budget-capped, so the
        loop converges.  Exhaustion raises — a silent drop here would be a
        mis-accounted claim."""
        last: Exception | None = None
        for _ in range(self.config.bind_attempts):
            try:
                return fn()
            except GangConflictError as exc:
                # A storm-broken gang commit: siblings were unwound, the
                # store is balanced, the whole gang is safe to replan.
                last = exc
            except AllocationError:
                raise
            except Exception as exc:  # noqa: BLE001 - injected Conflict/APIError
                last = exc
        raise SimAccountingError(f"{what}: retries exhausted: {last}")

    def _bind(self, claim: ResourceClaim, node: str, labels: dict) -> ResourceClaim:
        def attempt():
            # REFETCH each try: a failed update left the local copy's
            # allocation reset, but resourceVersion may have moved.
            current = self._retry(
                "get", lambda: self.server.get(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                )
            )
            return self.allocator.allocate(
                current, node_name=node, node_labels=labels
            )

        return self._retry(f"bind {claim.metadata.name}", attempt)

    def _unbind(self, name: str, namespace: str = "default") -> None:
        def attempt():
            current = self.server.get(ResourceClaim.KIND, name, namespace)
            if current.status.allocation is not None:
                self.allocator.deallocate(current)
            return True

        self._retry(f"release {name}", attempt)
        self._retry(
            f"delete {name}",
            lambda: self.server.delete(ResourceClaim.KIND, name, namespace),
        )

    # -- event handlers -----------------------------------------------------

    def _score_nodes(self, claim: ResourceClaim, candidates: list) -> list:
        """(score, -1*tie, name, labels, plan) per feasible candidate node,
        best first.  Every plan() call is timed — this IS the latency
        sample the report's p50/p90 comes from."""
        scored = []
        for name, labels, _ in candidates:
            t0 = time.perf_counter()
            try:
                plan = self.allocator.plan(claim, node_name=name, node_labels=labels)
            except AllocationError:
                self._plan_ms.append((time.perf_counter() - t0) * 1000.0)
                continue
            self._plan_ms.append((time.perf_counter() - t0) * 1000.0)
            total = objectives.score_plan(
                plan,
                weights=self.config.weights,
                power_table=self.config.power_table,
            ).total
            # Quantize to the extender's 0..10 wire contract: the
            # kube-scheduler never sees the float, so the simulator must
            # not rank on precision the real system cannot express.  The
            # coarse bins also make near-ties collapse onto the name
            # tie-break, the same first-fit concentration the extender's
            # deterministic node ordering produces in a real cluster.
            scored.append((round(10 * total), name, labels, plan))
        scored.sort(key=lambda t: (-t[0], t[1]))
        return scored

    def _arrive(self, now: float) -> None:
        cfg = self.config
        chips = self.rng.choices(
            [c for c, _ in cfg.claim_mix], [w for _, w in cfg.claim_mix]
        )[0]
        lifetime = self.rng.lognormvariate(cfg.lifetime_mu, cfg.lifetime_sigma)
        candidates = self.rng.sample(self.nodes, min(cfg.fanout, len(self.nodes)))
        if cfg.gang_fraction > 0 and self.rng.random() < cfg.gang_fraction:
            self._arrive_gang(now, chips, lifetime, candidates)
            return
        self.report.submitted += 1
        self._offered_cs += chips * lifetime
        claim = self._new_claim(chips)
        claim = self._retry(
            f"create {claim.metadata.name}", lambda: self.server.create(claim)
        )
        scored = self._score_nodes(claim, candidates)
        if not scored:
            self.report.infeasible += 1
            _SIM_CLAIMS.inc(outcome="infeasible")
            self._retry(
                f"delete {claim.metadata.name}",
                lambda: self.server.delete(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                ),
            )
            return
        _, node, labels, _ = scored[0]
        try:
            bound = self._bind(claim, node, labels)
        except AllocationError:
            # Lost a race against a concurrent event between plan and bind
            # (single-threaded here, so this is storm-driven state drift).
            self.report.infeasible += 1
            _SIM_CLAIMS.inc(outcome="infeasible")
            self._retry(
                f"delete {claim.metadata.name}",
                lambda: self.server.delete(
                    ResourceClaim.KIND, claim.metadata.name,
                    claim.metadata.namespace,
                ),
            )
            return
        self.report.bound += 1
        _SIM_CLAIMS.inc(outcome="bound")
        self._served_cs += chips * lifetime
        self._live[bound.metadata.name] = (chips, now + lifetime)
        heapq.heappush(
            self._events,
            (now + lifetime, self._seq(), _RELEASE, bound.metadata.name),
        )

    def _arrive_gang(self, now: float, chips: int, lifetime: float,
                     candidates: list) -> None:
        cfg = self.config
        self.report.gangs_submitted += 1
        size = min(cfg.gang_size, len(candidates))
        self.report.submitted += size
        self._offered_cs += chips * lifetime * size
        # Rank candidate nodes by a probe member's score, take the top
        # ``size`` distinct nodes as the gang's placement.
        probe = self._new_claim(chips)
        scored = self._score_nodes(probe, candidates)
        self._claim_seq -= 1  # probe claim was never created server-side
        if len(scored) < size:
            self.report.infeasible += size
            self.report.gangs_infeasible += 1
            _SIM_CLAIMS.inc(outcome="gang_infeasible")
            return
        members = []
        for _, node, labels, _ in scored[:size]:
            claim = self._new_claim(chips)
            claim = self._retry(
                f"create {claim.metadata.name}",
                lambda c=claim: self.server.create(c),
            )
            members.append(GangMember(claim=claim, node_name=node, node_labels=labels))
        try:
            committed = self._retry(
                "gang allocate", lambda: self._gang_attempt(members)
            )
        except (AllocationError, SimAccountingError):
            for m in members:
                self._retry(
                    f"delete {m.claim.metadata.name}",
                    lambda mm=m: self.server.delete(
                        ResourceClaim.KIND, mm.claim.metadata.name,
                        mm.claim.metadata.namespace,
                    ),
                )
            self.report.infeasible += size
            self.report.gangs_infeasible += 1
            _SIM_CLAIMS.inc(outcome="gang_infeasible")
            return
        self.report.gangs_committed += 1
        _SIM_CLAIMS.inc(outcome="gang_committed")
        for claim in committed:
            self.report.bound += 1
            _SIM_CLAIMS.inc(outcome="bound")
            self._served_cs += chips * lifetime
            self._live[claim.metadata.name] = (chips, now + lifetime)
            heapq.heappush(
                self._events,
                (now + lifetime, self._seq(), _RELEASE, claim.metadata.name),
            )

    def _gang_attempt(self, members: list) -> list:
        """One allocate_gang try with refetched members — after a storm
        unwind, the claims must be re-read (committed-then-unwound members
        have new resourceVersions and no allocation)."""
        fresh = []
        for m in members:
            current = self.server.get(
                ResourceClaim.KIND, m.claim.metadata.name,
                m.claim.metadata.namespace,
            )
            fresh.append(GangMember(
                claim=current, node_name=m.node_name, node_labels=m.node_labels,
            ))
        try:
            return self.allocator.allocate_gang(fresh)
        except GangConflictError as exc:
            # Typed conflict: the commit lost an optimistic-concurrency
            # race and every committed sibling was unwound (exc.unwound
            # has their names — no string matching).  Journal the wasted
            # work and re-raise; _retry() knows this one is replannable,
            # unlike a genuinely infeasible gang's plain AllocationError.
            self.report.gangs_unwound += 1
            _SIM_CLAIMS.inc(outcome="gang_unwound")
            JOURNAL.record(
                "cluster_sim", "gang.conflict",
                unwound=list(exc.unwound), error=str(exc),
            )
            raise

    def _release(self, name: str) -> None:
        self._unbind(name)
        self._live.pop(name, None)
        self.report.released += 1
        _SIM_CLAIMS.inc(outcome="released")

    # -- audits -------------------------------------------------------------

    def _audit(self) -> None:
        """Relist the store and reconcile against the ledger: every claim
        with an allocation must be exactly one live ledger entry and vice
        versa — the exactly-once accounting check."""
        self.report.audits += 1
        allocated = {
            c.metadata.name
            for c in self.server.list(ResourceClaim.KIND)
            if c.status.allocation is not None
        }
        ledger = set(self._live)
        if allocated != ledger:
            self.report.audit_failures += 1
            _SIM_AUDIT_FAILURES.inc()
            JOURNAL.record(
                "cluster_sim", "audit.mismatch",
                store_only=sorted(allocated - ledger)[:5],
                ledger_only=sorted(ledger - allocated)[:5],
            )
        self._sample_fragmentation()

    def _sample_fragmentation(self) -> None:
        """Stranded-free fraction over a seeded node sample: free chips
        that NO intact (fully-free) multi-chip subslice device covers.
        Also samples cluster utilization over the same nodes."""
        sample = self.rng.sample(
            self.nodes, min(self.config.sample_nodes, len(self.nodes))
        )
        stranded_total = 0
        free_total = 0
        chips_total = 0
        for name, labels, chips in sample:
            view = self.allocator.view(name, labels)
            free = set(view.node_markers) - view.used_markers
            chips_total += chips
            if not free:
                continue
            intact: set = set()
            for c in view.candidates:
                m = c.markers
                if len(m) >= 2 and not (m & view.used_markers):
                    intact |= m
            stranded_total += len(free - intact)
            free_total += len(free)
        if free_total:
            frac = stranded_total / free_total
            self._frag_samples.append(frac)
            _SIM_FRAG.set(frac)
        if chips_total:
            used = chips_total - free_total
            self._util_samples.append(used / chips_total)

    # -- main loop ----------------------------------------------------------

    def _seq(self) -> int:
        self._event_seq += 1
        return self._event_seq

    def run(self) -> SimReport:
        cfg = self.config
        wall0 = time.perf_counter()
        self._events: list = []
        self._event_seq = 0
        # Seed the schedule: first arrival, audits, storm windows.
        heapq.heappush(self._events, (0.0, self._seq(), _ARRIVE, None))
        t = cfg.audit_interval_s
        while t < cfg.duration_s:
            heapq.heappush(self._events, (t, self._seq(), _AUDIT, None))
            t += cfg.audit_interval_s
        for storm in cfg.storms:
            heapq.heappush(
                self._events, (storm.start_s, self._seq(), _STORM_ON, storm)
            )
            heapq.heappush(
                self._events,
                (storm.start_s + storm.duration_s, self._seq(), _STORM_OFF, storm),
            )
        JOURNAL.record(
            "cluster_sim", "run.begin", nodes=cfg.n_nodes, seed=cfg.seed,
            duration_s=cfg.duration_s, arrival_rate=cfg.arrival_rate,
        )
        while self._events:
            now, _, kind, payload = heapq.heappop(self._events)
            if kind == _ARRIVE:
                if now < cfg.duration_s:
                    self._arrive(now)
                    gap = self.rng.expovariate(cfg.arrival_rate)
                    heapq.heappush(
                        self._events, (now + gap, self._seq(), _ARRIVE, None)
                    )
            elif kind == _RELEASE:
                self._release(payload)
            elif kind == _AUDIT:
                self._audit()
            elif kind == _STORM_ON:
                self.injector.arm(payload.profile)
            elif kind == _STORM_OFF:
                self.injector.disarm(payload.profile.name)
        # Drain done (RELEASE events past duration_s still ran).  Disarm
        # everything and run the final audit: the cluster must be empty.
        self.injector.disarm()
        self._audit()
        self.report.leaked_claims = len(self._live) + sum(
            1
            for c in self.server.list(ResourceClaim.KIND)
            if c.status.allocation is not None
        )
        self._finalize(wall0)
        JOURNAL.record(
            "cluster_sim", "run.end", bound=self.report.bound,
            released=self.report.released,
            audit_failures=self.report.audit_failures,
            leaked=self.report.leaked_claims,
        )
        return self.report

    def _finalize(self, wall0: float) -> None:
        r = self.report
        r.plan_samples = len(self._plan_ms)
        r.plan_p50_ms = round(_percentile(self._plan_ms, 0.50), 3)
        r.plan_p90_ms = round(_percentile(self._plan_ms, 0.90), 3)
        r.packing_efficiency = round(
            self._served_cs / self._offered_cs if self._offered_cs else 0.0, 4
        )
        r.fragmentation = round(
            sum(self._frag_samples) / len(self._frag_samples)
            if self._frag_samples else 0.0, 4
        )
        r.fragmentation_final = round(
            self._frag_samples[-1] if self._frag_samples else 0.0, 4
        )
        r.utilization_mean = round(
            sum(self._util_samples) / len(self._util_samples)
            if self._util_samples else 0.0, 4
        )
        r.wall_s = round(time.perf_counter() - wall0, 2)
        _SIM_PACKING.set(r.packing_efficiency)

    def close(self) -> None:
        self.allocator.close()


def run_sim(config: SimConfig | None = None) -> SimReport:
    """Build, run, close — the one-call surface bench.py and the chaos
    suite use."""
    sim = ClusterSim(config)
    try:
        return sim.run()
    finally:
        sim.close()


# -- multi-scheduler contention harness ---------------------------------------
#
# ROADMAP item 4a: N scheduler threads race plan()/plan_gang()/
# allocate_gang() against ONE in-memory API server with real
# optimistic-concurrency semantics — every commit is a resourceVersion
# CAS, every cross-claim device race is adjudicated by an admission-time
# marker-exclusivity validator (both 409 on loss).  The harness measures
# conflict-retry convergence, wasted-work ratio and per-scheduler claim
# fairness (Jain's index), and carries the three contention-awareness
# levers the A/B quantifies:
#
# * seeded per-scheduler permutation of equal-score candidates
#   (objectives.shuffle_equal_scores) so ties stop concentrating every
#   scheduler on the same pool,
# * optional per-scheduler pool/work sharding with spill-over
#   (index.stable_shard),
# * contention-adaptive backoff shaping (retry.ContentionBackoff: grows
#   with observed 409 density, resets on success) vs the naive baseline
#   (exponential backoff that never resets — early losers inherit
#   compounding delays and starve).
#
# An ARMED -> COUNTING -> FIRED starvation detector (the scheduler twin
# of models/disagg.py's admission-deadlock watchdog) fires when a
# scheduler's conflict streak exceeds a budget with zero commits while
# siblings make progress: diag bundle, `sched.starved` journal line,
# dra_sched_starvation_total — then forced recovery (backoff reset), so
# a starving scheduler degrades loudly instead of wedging silently.

_SCHED_CONFLICTS = REGISTRY.counter(
    "dra_sched_conflicts_total",
    "Optimistic-concurrency conflicts (CAS 409s, validator rejections, "
    "injected storms) per contention-harness scheduler",
)
_SCHED_RETRY = REGISTRY.histogram(
    "dra_sched_retry_seconds",
    "Conflict-retry convergence per committed work item: first attempt "
    "to successful commit, retries and backoff included",
)
_SCHED_FAIRNESS = REGISTRY.gauge(
    "dra_sched_fairness",
    "Jain's fairness index over per-scheduler committed claims at the "
    "end of a contention run (1.0 = perfectly even)",
)
_SCHED_STARVATION = REGISTRY.counter(
    "dra_sched_starvation_total",
    "Starvation-detector firings: a scheduler exceeded its conflict "
    "budget with zero commits while siblings progressed",
)


def jain_fairness(counts: list) -> float:
    """Jain's fairness index ``(sum x)^2 / (n * sum x^2)`` over
    per-scheduler committed-claim counts: 1.0 when every scheduler
    commits the same amount, ->1/n when one scheduler takes everything.
    An all-zero vector is vacuously fair (nothing was committed to share
    unevenly)."""
    if not counts:
        return 1.0
    sq = sum(x * x for x in counts)
    if sq == 0:
        return 1.0
    total = sum(counts)
    return (total * total) / (len(counts) * sq)


class DeviceExclusivityValidator:
    """Admission-time device-marker non-overlap check for ResourceClaim
    status writes — the store-side arbiter that makes cross-claim device
    races LOSE with a 409 instead of silently double-booking.

    Claim-level CAS already serializes two schedulers racing the SAME
    claim; what it cannot catch is two schedulers committing DIFFERENT
    claims onto the same chip in the plan-to-commit window.  A real
    apiserver would delegate that to a validating admission plugin; this
    is its in-process analog: registered via
    ``InMemoryAPIServer.add_update_validator``, it runs under the store
    lock between the resourceVersion check and the mutation, tracking
    ``(pool, chip-marker) -> claim`` ownership from allocation deltas
    (deallocation releases markers, so gang unwinds hand capacity back).
    All-or-nothing per write: every newly claimed marker is checked
    before any is recorded.  Deletes of still-allocated claims are not
    tracked — the harness only deletes claims it has deallocated or
    at teardown."""

    def __init__(self, server: InMemoryAPIServer, device_markers: Optional[dict] = None):
        # ``device_markers`` lets an A/B harness scan the (static) slice
        # inventory once and share the map across runs — at 10k pools the
        # scan's deep-copied LIST dominates validator setup, not the check.
        if device_markers is None:
            device_markers = self.scan_markers(server)
        self._device_markers = device_markers
        self._held: dict = {}  # (pool, marker) -> claim name
        self.conflicts = 0  # mutated under the server lock
        self._remove = server.add_update_validator(
            ResourceClaim.KIND, self._validate
        )

    @staticmethod
    def scan_markers(server: InMemoryAPIServer) -> dict:
        """Map ``(driver, pool, device) -> ((pool, chip-marker), ...)`` from
        the published ResourceSlices."""
        out: dict = {}
        for s in server.list(ResourceSlice.KIND):
            pool = s.spec.pool.name
            for d in s.spec.devices:
                out[(s.spec.driver, pool, d.name)] = tuple(
                    (pool, cap)
                    for cap in d.basic.capacity
                    if cap.startswith("chip")
                )
        return out

    def close(self) -> None:
        self._remove()

    def markers_of(self, claim) -> set:
        out: set = set()
        alloc = claim.status.allocation
        if alloc is None:
            return out
        for r in alloc.devices.results:
            out.update(self._device_markers.get((r.driver, r.pool, r.device), ()))
        return out

    def _validate(self, current, updated) -> None:
        from k8s_dra_driver_tpu.kube.fakeserver import Conflict

        name = updated.metadata.name
        old_m = self.markers_of(current)
        new_m = self.markers_of(updated)
        if new_m == old_m:
            return  # reservation/status touch, no allocation delta
        for m in new_m - old_m:
            owner = self._held.get(m)
            if owner is not None and owner != name:
                self.conflicts += 1
                raise Conflict(
                    f"admission validator: device marker {m!r} already "
                    f"held by {owner!r}"
                )
        for m in old_m - new_m:
            if self._held.get(m) == name:
                del self._held[m]
        for m in new_m - old_m:
            self._held[m] = name


@dataclass(frozen=True)
class _WorkItem:
    """One unit of contended scheduling work: a single claim or a gang of
    ``len(names)`` claims that must commit atomically on distinct nodes."""

    id: int
    kind: str  # "single" | "gang"
    names: tuple
    namespace: str
    chips: int


@dataclass
class ContentionConfig:
    seed: int = 0
    n_nodes: int = 1000
    node_mix: tuple = (0.35, 0.35, 0.30)
    n_schedulers: int = 4
    work_items: int = 96  # single-claim work items in the shared backlog
    gang_items: int = 12  # gang work items (gang_size claims each)
    gang_size: int = 3
    claim_mix: tuple = ((1, 0.50), (2, 0.30), (4, 0.20))
    fanout: int = 2  # candidate nodes scored per attempt
    weights: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_WEIGHTS)
    )
    power_table: dict = field(
        default_factory=lambda: dict(objectives.DEFAULT_POWER_TABLE)
    )
    # The A/B switch.  True = shuffled ties + pool/work sharding with
    # spill-over + density-shaped backoff that resets on success.  False
    # = deterministic (-score, name) ordering, head-of-line work pickup,
    # exponential backoff that never resets (the documented anti-pattern
    # Backoff.reset() exists to prevent).
    conflict_aware: bool = True
    shard_pools: bool = True  # per-scheduler sharding lever (aware only)
    max_attempts: int = 600  # per work item; exhaustion raises, loudly
    # Starvation detector: consecutive conflict rounds with zero commits
    # while siblings progress before the watchdog fires.
    starvation_budget: int = 16
    storm: tuple = ()  # FaultProfiles armed for the whole run
    naive_base_delay_s: float = 0.008
    naive_max_delay_s: float = 0.4
    aware_base_delay_s: float = 0.001
    aware_max_delay_s: float = 0.03


def default_contention_storm(n_schedulers: int = 8) -> tuple:
    """The ``make sim-contention`` fairness storm: an ASYMMETRIC
    budget-capped 409 burst that hits the first three quarters of the
    schedulers at the commit seam, plus a small unlimited commit latency
    that widens every scheduler's plan-to-commit window — the window
    genuine CAS and validator races live in.

    The burst is identical across both A/B halves (same profile, fresh
    budget); what differs is RESILIENCE.  The conflict-aware backoff's
    short density-shaped cap keeps victims attempting, so the burst
    budget burns out quickly and the first post-burst success resets
    them to full speed — fairness recovers.  The naive never-reset
    exponential converts the same transient burst into a permanent
    speed handicap: victims compound to the delay cap during the burst
    and stay there for the rest of the run, so a storm that injected a
    bounded number of 409s ends up deciding the whole allocation —
    Jain's index collapses.  The starvation tests arm their own
    scoped single-victim profile instead."""
    victims = tuple(range(max(1, (3 * n_schedulers) // 4)))
    return (
        FaultProfile(
            name="sched-409-storm",
            sched_conflict_rate=0.6,
            schedulers=victims,
            limit=100,
        ),
        FaultProfile(
            name="sched-commit-latency", sched_commit_latency_s=0.010,
        ),
    )


def uniform_contention_storm() -> tuple:
    """A symmetric storm for the wasted-work A/B (``bench.py
    plan_scale``): every scheduler eats the same seeded 409 density, so
    the waste ratio isolates how much planning each policy throws away
    rather than who got unlucky.  Under this storm the naive policy
    wastes work by planning against a stale inventory view (staleness
    discovered at write time, healed by re-get), while the aware policy
    refetches per attempt and decorrelates candidate choice."""
    return (
        FaultProfile(
            name="sched-409-storm", sched_conflict_rate=0.10, limit=300,
        ),
        FaultProfile(
            name="sched-commit-latency", sched_commit_latency_s=0.010,
        ),
    )


@dataclass
class ContentionReport:
    n_nodes: int = 0
    n_schedulers: int = 0
    seed: int = 0
    conflict_aware: bool = False
    work_singles: int = 0
    work_gangs: int = 0
    claims_total: int = 0
    committed_claims: int = 0
    commits_by_scheduler: dict = field(default_factory=dict)
    items_by_scheduler: dict = field(default_factory=dict)
    conflicts_by_scheduler: dict = field(default_factory=dict)
    conflicts_total: int = 0
    gang_conflicts: int = 0  # typed GangConflictError unwinds observed
    attempts_total: int = 0
    wasted_attempts: int = 0
    wasted_work_ratio: float = 0.0
    fairness: float = 0.0
    convergence_s: float = 0.0
    plan_samples: int = 0
    plan_p50_ms: float = 0.0
    plan_p90_ms: float = 0.0
    starved: list = field(default_factory=list)
    starvation_bundles: list = field(default_factory=list)
    lost_claims: int = 0
    double_committed: int = 0
    marker_overlaps: int = 0
    validator_conflicts: int = 0
    injected_conflicts: int = 0

    def to_json(self) -> str:
        return json.dumps(self.__dict__, sort_keys=True)


class _SchedulerWorker:
    """One racing scheduler: its own Allocator (shared index — see
    Allocator.__init__), its own seeded rng, its own backoff, its own
    starvation detector.  All cross-worker state lives on the sim."""

    def __init__(self, sim: "ContentionSim", idx: int):
        self.sim = sim
        self.idx = idx
        # Metric label values must be bounded, precomputed strings (one
        # per scheduler index), never formatted at the call site.
        self.label = "sched-%d" % idx
        cfg = sim.config
        self.rng = random.Random(cfg.seed * 7919 + idx)
        self.allocator = Allocator(sim.server, index=sim.index)
        self._aware = cfg.conflict_aware
        if self._aware:
            self.backoff = ContentionBackoff(
                base_delay_s=cfg.aware_base_delay_s,
                max_delay_s=cfg.aware_max_delay_s,
                rng=self.rng,
            )
        else:
            self.backoff = Backoff(
                RetryPolicy(
                    base_delay_s=cfg.naive_base_delay_s,
                    max_delay_s=cfg.naive_max_delay_s,
                    multiplier=2.0,
                    jitter=0.5,
                ),
                rng=self.rng,
            )
        shard = cfg.conflict_aware and cfg.shard_pools and cfg.n_schedulers > 1
        self.shard_nodes = (
            [n for n in sim.nodes if stable_shard(n[0], cfg.n_schedulers) == idx]
            if shard else sim.nodes
        )
        # Work sharding is round-robin by item id (exact ±1 balance);
        # POOL sharding uses stable_shard so every scheduler derives the
        # same node partition without coordination.
        self.shard_items = (
            [it for it in sim.work if it.id % cfg.n_schedulers == idx]
            if shard else list(sim.work)
        )
        self.spill_start = (idx * len(sim.work)) // max(1, cfg.n_schedulers)
        # tallies (ints: cross-thread reads are atomic enough for the
        # sibling-progress signal; authoritative totals come after join)
        self.commits = 0
        self.items_won = 0
        self.conflicts = 0
        self.gang_conflicts = 0
        self.attempts = 0
        self.plan_ms: list = []
        self.error: Exception | None = None
        # starvation detector (ARMED -> COUNTING -> FIRED)
        self.det_state = "ARMED"
        self._streak = 0
        self._sib_mark = 0
        self.det_fired = False
        self.bundles: list = []

    # -- the racing loop ---------------------------------------------------

    def run(self) -> None:
        try:
            while True:
                item = self.sim.next_item(self)
                if item is None:
                    return
                self._attempt_item(item)
        except Exception as exc:  # noqa: BLE001 - surfaced after join
            self.error = exc

    def _attempt_item(self, item: _WorkItem) -> None:
        sim = self.sim
        cfg = sim.config
        t0 = time.perf_counter()
        fresh: list | None = None
        for _ in range(cfg.max_attempts):
            if sim.is_done(item):
                return
            # Freshness discipline is itself part of the A/B.  Aware:
            # REFETCH every member every attempt, so a sibling's commit
            # is discovered before any planning is spent.  Naive: plan
            # against the view in hand and let the resourceVersion CAS
            # discover staleness at write time (the wasted scheduling
            # cycle a lagging informer cache costs a real multi-scheduler
            # cluster); a Conflict is healed by re-get — is_retryable's
            # contract — so the refetch happens on the NEXT attempt.
            if fresh is None or self._aware:
                fresh = []
                taken = False
                for name in item.names:
                    c = sim.server.get(ResourceClaim.KIND, name, item.namespace)
                    if c.status.allocation is not None:
                        taken = True
                        break
                    fresh.append(c)
                if taken:
                    sim.mark_observed(item)
                    return
            members = self._plan_placement(item, fresh)
            if members is None:
                # Feasibility miss in this fanout sample, not a conflict:
                # resample.  No backoff — the replan IS the wait.
                self.attempts += 1
                continue
            self.attempts += 1
            try:
                sim.injector.before_sched_commit(self.idx)
                if item.kind == "gang":
                    self.allocator.allocate_gang(members)
                else:
                    m = members[0]
                    self.allocator.allocate(
                        m.claim, node_name=m.node_name, node_labels=m.node_labels
                    )
            except GangConflictError as exc:
                self.gang_conflicts += 1
                JOURNAL.record_lazy(
                    "cluster_sim", "gang.conflict", correlation=self.label,
                    attrs=lambda exc=exc: dict(
                        unwound=list(exc.unwound), error=str(exc),
                    ),
                )
                self._on_conflict()
                fresh = None  # heal staleness by re-get next attempt
                continue
            except (Conflict, AllocationError):
                # Claim-level CAS loss, validator rejection, injected 409,
                # or a plan gone stale mid-commit: all replannable.
                self._on_conflict()
                fresh = None
                continue
            sim.mark_won(item, self)
            self.items_won += 1
            self.commits += len(item.names)
            _SCHED_RETRY.observe(time.perf_counter() - t0)
            self._on_success()
            return
        raise SimAccountingError(
            f"{self.label}: work item {item.names[0]!r}: "
            f"{cfg.max_attempts} attempts exhausted"
        )

    def _plan_placement(self, item: _WorkItem, fresh: list):
        """Score a candidate sample (shard-preferred when aware) for this
        item's probe claim; spill over to the full node set when the own
        shard can't satisfy.  Returns GangMembers or None if infeasible
        in this sample."""
        size = len(fresh)
        scored = self._score(fresh[0], self.sim.sample_candidates(self, size))
        if len(scored) < size and self.shard_nodes is not self.sim.nodes:
            # Spill-over: the shard is exhausted or unlucky — rescore
            # against a sample drawn from every pool.
            scored = self._score(
                fresh[0], self.sim.sample_candidates(self, size, spill=True)
            )
        if len(scored) < size:
            return None
        return [
            GangMember(claim=c, node_name=name, node_labels=labels)
            for c, (_, name, labels, _) in zip(fresh, scored[:size])
        ]

    def _score(self, claim, candidates: list) -> list:
        cfg = self.sim.config
        scored = []
        for name, labels, _ in candidates:
            t0 = time.perf_counter()
            try:
                plan = self.allocator.plan(
                    claim, node_name=name, node_labels=labels
                )
            except AllocationError:
                self.plan_ms.append((time.perf_counter() - t0) * 1000.0)
                continue
            self.plan_ms.append((time.perf_counter() - t0) * 1000.0)
            total = objectives.score_plan(
                plan, weights=cfg.weights, power_table=cfg.power_table
            ).total
            # Same 0..10 extender quantization as ClusterSim._score_nodes:
            # coarse bins make ties common, which is exactly what the
            # conflict-aware shuffle decorrelates.
            scored.append((round(10 * total), name, labels, plan))
        scored.sort(key=lambda t: (-t[0], t[1]))
        if self._aware and cfg.n_schedulers > 1:
            scored = objectives.shuffle_equal_scores(scored, self.rng)
        return scored

    # -- conflict/starvation bookkeeping -----------------------------------

    def _on_conflict(self) -> None:
        self.conflicts += 1
        _SCHED_CONFLICTS.inc(scheduler=self.label)
        self._starvation_tick()
        if self._aware:
            self.backoff.on_conflict()
        self.backoff.sleep()  # naive Backoff: grows per call, NEVER reset

    def _on_success(self) -> None:
        self.det_state = "ARMED"
        self._streak = 0
        if self._aware:
            self.backoff.on_success()
        # The naive baseline deliberately skips Backoff.reset() here —
        # that omission is the anti-pattern the A/B quantifies.

    def _starvation_tick(self) -> None:
        """ARMED -> COUNTING -> FIRED, the scheduler twin of
        models/disagg.py's admission-deadlock tick: COUNTING only
        advances while siblings commit (a globally stalled store is a
        storm, not starvation), any own commit re-ARMs, and firing is
        once per scheduler — bundle, journal, metric, then forced
        recovery (backoff reset) so the starved scheduler re-enters the
        race at base cadence instead of wedging."""
        if self.det_fired:
            return
        sib = self.sim.sibling_commits(self)
        if self.det_state == "ARMED":
            self.det_state = "COUNTING"
            self._streak = 0
            self._sib_mark = sib
            return
        if sib > self._sib_mark:
            self._streak += 1
            self._sib_mark = sib
        if self._streak < self.sim.config.starvation_budget:
            return
        state = dict(
            scheduler=self.label,
            conflicts=self.conflicts,
            streak=self._streak,
            commits=self.commits,
            sibling_commits=sib,
            conflict_aware=self._aware,
        )
        try:
            from k8s_dra_driver_tpu.utils.watchdog import (
                WATCHDOG,
                dump_diag_bundle,
            )

            self.bundles.append(dump_diag_bundle(
                WATCHDOG.bundle_dir, reason="sched_starvation",
                correlation=self.label, state=state,
            ))
        except Exception:  # noqa: BLE001 - diagnostics never block recovery
            pass
        JOURNAL.record(
            "cluster_sim", "sched.starved", correlation=self.label, **state
        )
        _SCHED_STARVATION.inc(scheduler=self.label)
        self.det_fired = True
        self.det_state = "FIRED"
        self._streak = 0
        if self._aware:
            self.backoff.on_success()
        else:
            self.backoff.reset()  # forced recovery: shed compounded delay

    def close(self) -> None:
        self.allocator.close()  # no-op for the shared index; future-proof


class ContentionSim:
    """One seeded multi-scheduler contention run.

    Interleaving is real (threads), so unlike ClusterSim the REPORT is
    not bit-deterministic — tests assert invariants (exactly-once
    commits, fairness bounds, detector fired/silent), not equality.
    What IS seeded: the inventory, the backlog, every per-scheduler rng
    (candidate sampling, tie shuffles, jitter) and the fault storm.

    Pass ``server``/``nodes``/``index`` to reuse a built cluster across
    runs (the 10k-pool A/B builds once, runs naive, resets claims, runs
    aware); the sim then leaves them open on close()."""

    def __init__(
        self,
        config: ContentionConfig | None = None,
        *,
        run_tag: str = "run",
        server: InMemoryAPIServer | None = None,
        nodes: list | None = None,
        index: AllocationIndex | None = None,
        device_markers: dict | None = None,
    ):
        self.config = config or ContentionConfig()
        cfg = self.config
        self.rng = random.Random(cfg.seed)
        self._owns_cluster = server is None
        if server is None:
            self.injector = FaultInjector(seed=cfg.seed + 1)
            self.server = InMemoryAPIServer(fault_injector=self.injector)
            install_device_classes(self.server)
            self.nodes, self.total_chips = build_synthetic_cluster(
                self.server, self.rng, cfg.n_nodes, cfg.node_mix
            )
        else:
            self.server = server
            if self.server.faults is None:
                self.server.faults = FaultInjector(seed=cfg.seed + 1)
            self.injector = self.server.faults
            self.nodes = list(nodes or [])
            self.total_chips = sum(c for _, _, c in self.nodes)
        self._owns_index = index is None
        self.index = index if index is not None else AllocationIndex(self.server)
        self.validator = DeviceExclusivityValidator(
            self.server, device_markers=device_markers
        )
        self.run_tag = run_tag
        self.work: list[_WorkItem] = []
        self._build_backlog()
        self._work_lock = threading.Lock()
        self._winners: dict[int, str] = {}  # item id -> scheduler label
        self._observed: set[int] = set()
        self._decided: set[int] = set()
        self.double_committed = 0
        self.workers = [
            _SchedulerWorker(self, i) for i in range(cfg.n_schedulers)
        ]
        self.report = ContentionReport(
            n_nodes=cfg.n_nodes if self._owns_cluster else len(self.nodes),
            n_schedulers=cfg.n_schedulers,
            seed=cfg.seed,
            conflict_aware=cfg.conflict_aware,
            work_singles=cfg.work_items,
            work_gangs=cfg.gang_items,
            claims_total=sum(len(it.names) for it in self.work),
        )

    # -- backlog -----------------------------------------------------------

    def _claim_for(self, name: str, chips: int) -> ResourceClaim:
        if chips <= 1:
            return simple_claim(name, device_class=TPU_CLASS, count=1)
        return simple_claim(
            name,
            device_class=SUBSLICE_CLASS,
            count=1,
            selectors=[
                f"device.attributes['{DRIVER_NAME}'].chipCount == {chips}"
            ],
        )

    def _build_backlog(self) -> None:
        cfg = self.config
        item_id = 0
        for i in range(cfg.work_items):
            chips = self.rng.choices(
                [c for c, _ in cfg.claim_mix], [w for _, w in cfg.claim_mix]
            )[0]
            name = f"cont-{self.run_tag}-w{i:04d}"
            claim = self.server.create(self._claim_for(name, chips))
            self.work.append(_WorkItem(
                id=item_id, kind="single", names=(name,),
                namespace=claim.metadata.namespace, chips=chips,
            ))
            item_id += 1
        for g in range(cfg.gang_items):
            chips = self.rng.choices(
                [c for c, _ in cfg.claim_mix], [w for _, w in cfg.claim_mix]
            )[0]
            names = tuple(
                f"cont-{self.run_tag}-g{g:03d}-m{j}"
                for j in range(cfg.gang_size)
            )
            ns = ""
            for n in names:
                created = self.server.create(self._claim_for(n, chips))
                ns = created.metadata.namespace
            self.work.append(_WorkItem(
                id=item_id, kind="gang", names=names, namespace=ns, chips=chips,
            ))
            item_id += 1

    # -- shared work/win state (called from worker threads) ----------------

    def next_item(self, worker: _SchedulerWorker):
        """The next undecided work item for ``worker``.  Naive: everyone
        scans the same head-of-line order (maximal contention).  Aware:
        own shard first, then spill over into the leftovers starting at a
        per-scheduler rotation so spillers don't re-converge on one
        item."""
        cfg = self.config
        with self._work_lock:
            decided = self._decided
            if len(decided) >= len(self.work):
                return None
            if cfg.conflict_aware and cfg.shard_pools and cfg.n_schedulers > 1:
                for it in worker.shard_items:
                    if it.id not in decided:
                        return it
                n = len(self.work)
                for k in range(n):
                    it = self.work[(worker.spill_start + k) % n]
                    if it.id not in decided:
                        return it
                return None
            for it in self.work:
                if it.id not in decided:
                    return it
            return None

    def is_done(self, item: _WorkItem) -> bool:
        with self._work_lock:
            return item.id in self._decided

    def mark_observed(self, item: _WorkItem) -> None:
        with self._work_lock:
            self._observed.add(item.id)
            self._decided.add(item.id)

    def mark_won(self, item: _WorkItem, worker: _SchedulerWorker) -> None:
        with self._work_lock:
            prev = self._winners.get(item.id)
            if prev is not None and prev != worker.label:
                # Two schedulers both think they committed one item — the
                # exactly-once property is broken; count loudly.
                self.double_committed += 1
            self._winners[item.id] = worker.label
            self._decided.add(item.id)

    def sibling_commits(self, worker: _SchedulerWorker) -> int:
        return sum(w.commits for w in self.workers if w is not worker)

    def sample_candidates(
        self, worker: _SchedulerWorker, size: int, spill: bool = False
    ) -> list:
        pool = self.nodes if spill else worker.shard_nodes
        k = min(max(self.config.fanout, size), len(pool))
        return worker.rng.sample(pool, k)

    # -- run ---------------------------------------------------------------

    def run(self) -> ContentionReport:
        cfg = self.config
        for profile in cfg.storm:
            # Arm a fresh copy: config storm profiles are templates, and
            # ``injected`` must start at 0 so an A/B pair reusing one
            # config gives BOTH runs the full budget.
            self.injector.arm(dataclasses.replace(profile, injected=0))
        # Injector stats accumulate for the injector's lifetime; snapshot
        # so a shared-server A/B reports per-run injection counts.
        self._stats0 = dict(self.injector.stats())
        JOURNAL.record(
            "cluster_sim", "contention.begin", correlation=self.run_tag,
            schedulers=cfg.n_schedulers, nodes=len(self.nodes),
            items=len(self.work), conflict_aware=cfg.conflict_aware,
        )
        wall0 = time.perf_counter()
        threads = [
            threading.Thread(
                target=w.run, name=f"contention-{w.label}", daemon=True
            )
            for w in self.workers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self.report.convergence_s = round(time.perf_counter() - wall0, 3)
        for profile in cfg.storm:
            self.injector.disarm(profile.name)
        for w in self.workers:
            if w.error is not None:
                raise SimAccountingError(
                    f"{w.label} died: {type(w.error).__name__}: {w.error}"
                ) from w.error
        self._finalize()
        self._audit()
        JOURNAL.record(
            "cluster_sim", "contention.end", correlation=self.run_tag,
            committed=self.report.committed_claims,
            conflicts=self.report.conflicts_total,
            fairness=self.report.fairness,
            wasted=self.report.wasted_attempts,
            starved=list(self.report.starved),
        )
        return self.report

    def _finalize(self) -> None:
        r = self.report
        plan_ms: list = []
        for w in self.workers:
            r.commits_by_scheduler[w.label] = w.commits
            r.items_by_scheduler[w.label] = w.items_won
            r.conflicts_by_scheduler[w.label] = w.conflicts
            r.conflicts_total += w.conflicts
            r.gang_conflicts += w.gang_conflicts
            r.attempts_total += w.attempts
            r.committed_claims += w.commits
            plan_ms.extend(w.plan_ms)
            if w.det_fired:
                r.starved.append(w.label)
                r.starvation_bundles.extend(w.bundles)
        successes = sum(w.items_won for w in self.workers)
        r.wasted_attempts = max(0, r.attempts_total - successes)
        r.wasted_work_ratio = round(
            r.wasted_attempts / r.attempts_total if r.attempts_total else 0.0,
            4,
        )
        r.fairness = round(
            jain_fairness([w.commits for w in self.workers]), 4
        )
        _SCHED_FAIRNESS.set(r.fairness)
        r.plan_samples = len(plan_ms)
        r.plan_p50_ms = round(_percentile(plan_ms, 0.50), 3)
        r.plan_p90_ms = round(_percentile(plan_ms, 0.90), 3)
        with self._work_lock:  # workers are joined, but keep the discipline
            r.double_committed = self.double_committed
        r.validator_conflicts = self.validator.conflicts
        stats = self.injector.stats()
        base = getattr(self, "_stats0", {})
        r.injected_conflicts = sum(
            stats.get(k, 0) - base.get(k, 0) for k in ("sched_conflict", "conflict")
        )

    def _audit(self) -> None:
        """Exactly-once accounting against the STORE, not the workers'
        tallies: every backlog claim allocated exactly once, device
        markers pairwise disjoint, winner attribution covering every
        item.  Lost or double-committed claims are counted (and asserted
        zero by the acceptance tests), never silently healed."""
        r = self.report
        own = {n for it in self.work for n in it.names}
        seen_markers: dict = {}
        allocated = set()
        for c in self.server.list(ResourceClaim.KIND):
            name = c.metadata.name
            if name not in own:
                continue
            if c.status.allocation is None:
                continue
            allocated.add(name)
            for m in self.validator.markers_of(c):
                if m in seen_markers:
                    r.marker_overlaps += 1
                    JOURNAL.record(
                        "cluster_sim", "contention.overlap",
                        marker=list(m), claims=[seen_markers[m], name],
                    )
                seen_markers[m] = name
        r.lost_claims = len(own - allocated)
        with self._work_lock:  # workers are joined, but keep the discipline
            won_items = set(self._winners)
        for it in self.work:
            if it.id not in won_items and any(
                n in allocated for n in it.names
            ):
                # Allocated in the store but no worker claims the win:
                # accounting hole, count as lost attribution.
                r.double_committed += 0  # keep counter semantics; fall through
                JOURNAL.record(
                    "cluster_sim", "contention.unattributed",
                    item=list(it.names),
                )

    def close(self) -> None:
        for w in self.workers:
            w.close()
        self.validator.close()
        if self._owns_index:
            self.index.close()


def run_contention(
    config: ContentionConfig | None = None, **kwargs
) -> ContentionReport:
    """Build, run, close — the one-call surface for tests and bench."""
    sim = ContentionSim(config, **kwargs)
    try:
        return sim.run()
    finally:
        sim.close()


def run_contention_ab(base: ContentionConfig) -> tuple:
    """Naive vs conflict-aware on ONE built cluster (built once — at 10k
    pools the inventory replay, not the racing, is the wall-clock): runs
    the naive config, deletes its claims (deallocating is unnecessary —
    delete events clear the index, and each run gets a fresh admission
    validator), then runs the aware config on the same seed.  Returns
    ``(naive_report, aware_report)``."""
    replace = dataclasses.replace
    rng = random.Random(base.seed)
    injector = FaultInjector(seed=base.seed + 1)
    server = InMemoryAPIServer(fault_injector=injector)
    install_device_classes(server)
    nodes, _ = build_synthetic_cluster(server, rng, base.n_nodes, base.node_mix)
    index = AllocationIndex(server)
    markers = DeviceExclusivityValidator.scan_markers(server)
    out = []
    try:
        for aware in (False, True):
            cfg = replace(base, conflict_aware=aware)
            tag = "aware" if aware else "naive"
            sim = ContentionSim(
                cfg,
                run_tag=tag,
                server=server,
                nodes=nodes,
                index=index,
                device_markers=markers,
            )
            try:
                out.append(sim.run())
            finally:
                sim.close()
            for c in server.list(ResourceClaim.KIND):
                server.delete(
                    ResourceClaim.KIND, c.metadata.name, c.metadata.namespace
                )
    finally:
        index.close()
    return tuple(out)
