"""CEL-subset evaluator for DRA device selectors.

The upstream kube-scheduler evaluates DeviceClass and per-request CEL
selectors against published device attributes (SURVEY.md §3.5; reference
DeviceClass example: ``device.driver == 'gpu.nvidia.com' && ...`` in
deployments/helm/.../deviceclass-gpu.yaml).  This module implements the
subset of CEL those selectors actually use, so the in-repo allocator and the
demo harness can run the same expressions a real cluster would:

* literals: int, float, string (single/double quoted), bool, null, lists
* operators: ``|| && ! == != < <= > >= in + - * / %``, ternary ``?:``
* member access ``a.b``, indexing ``a['k']`` / ``a[0]``
* functions: ``size(x)``, ``x.matches(re)``, ``x.startsWith(s)``,
  ``x.endsWith(s)``, ``x.contains(s)``, ``quantity(s)`` (k8s resource
  quantity → integer base units, so capacity comparisons like
  ``device.capacity['d'].hbm >= quantity('16Gi')`` work — the allocator
  exposes capacities pre-parsed to integers for exactly this)

Evaluation errors (unknown identifier, missing map key) raise
:class:`CELError`; per CEL-in-k8s semantics the caller treats an erroring
selector as non-matching.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

__all__ = ["CELError", "evaluate", "compile_expr"]


class CELError(Exception):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<float>\d+\.\d+)
  | (?P<int>\d+)
  | (?P<string>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op>\|\||&&|==|!=|<=|>=|[<>!+\-*/%?:.,\[\]()])
    """,
    re.VERBOSE,
)

_KEYWORDS = {"true": True, "false": False, "null": None}


@dataclass
class Token:
    kind: str  # 'int' | 'float' | 'string' | 'ident' | 'op' | 'end'
    value: Any


def _lex(src: str) -> list[Token]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise CELError(f"lex error at {src[pos:pos+10]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "int":
            out.append(Token("lit", int(text)))
        elif kind == "float":
            out.append(Token("lit", float(text)))
        elif kind == "string":
            body = text[1:-1]
            body = re.sub(r"\\(.)", r"\1", body)
            out.append(Token("lit", body))
        elif kind == "ident":
            if text in _KEYWORDS:
                out.append(Token("lit", _KEYWORDS[text]))
            else:
                out.append(Token("ident", text))
        else:
            out.append(Token("op", text))
    out.append(Token("end", None))
    return out


# ---------------------------------------------------------------------------
# Pratt parser → nested tuples (op, args...)
# ---------------------------------------------------------------------------

_BINARY_PRECEDENCE = {
    "||": 1,
    "&&": 2,
    "==": 3, "!=": 3, "<": 3, "<=": 3, ">": 3, ">=": 3, "in": 3,
    "+": 4, "-": 4,
    "*": 5, "/": 5, "%": 5,
}

_TERNARY_PRECEDENCE = 0.5


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.tokens = tokens
        self.pos = 0

    def peek(self) -> Token:
        return self.tokens[self.pos]

    def next(self) -> Token:
        tok = self.tokens[self.pos]
        self.pos += 1
        return tok

    def expect(self, value: str) -> None:
        tok = self.next()
        if tok.kind != "op" or tok.value != value:
            raise CELError(f"expected {value!r}, got {tok.value!r}")

    def parse(self):
        expr = self.parse_expr(0)
        if self.peek().kind != "end":
            raise CELError(f"trailing input at {self.peek().value!r}")
        return expr

    def parse_expr(self, min_prec):
        left = self.parse_unary()
        while True:
            tok = self.peek()
            op = tok.value if tok.kind == "op" else ("in" if (tok.kind, tok.value) == ("ident", "in") else None)
            if op == "?" and _TERNARY_PRECEDENCE >= min_prec:
                self.next()
                then = self.parse_expr(0)
                self.expect(":")
                otherwise = self.parse_expr(_TERNARY_PRECEDENCE)
                left = ("?:", left, then, otherwise)
                continue
            prec = _BINARY_PRECEDENCE.get(op)
            if prec is None or prec < min_prec:
                return left
            self.next()
            right = self.parse_expr(prec + 1)
            left = (op, left, right)

    def parse_unary(self):
        tok = self.peek()
        if tok.kind == "op" and tok.value == "!":
            self.next()
            return ("!", self.parse_unary())
        if tok.kind == "op" and tok.value == "-":
            self.next()
            return ("neg", self.parse_unary())
        return self.parse_postfix(self.parse_primary())

    def parse_primary(self):
        tok = self.next()
        if tok.kind == "lit":
            return ("lit", tok.value)
        if tok.kind == "ident":
            return ("var", tok.value)
        if tok.kind == "op" and tok.value == "(":
            inner = self.parse_expr(0)
            self.expect(")")
            return inner
        if tok.kind == "op" and tok.value == "[":
            items = []
            if not (self.peek().kind == "op" and self.peek().value == "]"):
                while True:
                    items.append(self.parse_expr(0))
                    if self.peek().kind == "op" and self.peek().value == ",":
                        self.next()
                        continue
                    break
            self.expect("]")
            return ("list", items)
        raise CELError(f"unexpected token {tok.value!r}")

    def parse_postfix(self, expr):
        while True:
            tok = self.peek()
            if tok.kind == "op" and tok.value == ".":
                self.next()
                name = self.next()
                if name.kind != "ident":
                    raise CELError(f"expected member name, got {name.value!r}")
                if self.peek().kind == "op" and self.peek().value == "(":
                    self.next()
                    args = []
                    if not (self.peek().kind == "op" and self.peek().value == ")"):
                        while True:
                            args.append(self.parse_expr(0))
                            if self.peek().kind == "op" and self.peek().value == ",":
                                self.next()
                                continue
                            break
                    self.expect(")")
                    expr = ("call", name.value, expr, args)
                else:
                    expr = ("member", expr, name.value)
            elif tok.kind == "op" and tok.value == "[":
                self.next()
                index = self.parse_expr(0)
                self.expect("]")
                expr = ("index", expr, index)
            elif tok.kind == "op" and tok.value == "(":
                # bare function call — only size() is global
                if expr[0] != "var":
                    raise CELError("only simple function calls supported")
                self.next()
                args = []
                if not (self.peek().kind == "op" and self.peek().value == ")"):
                    while True:
                        args.append(self.parse_expr(0))
                        if self.peek().kind == "op" and self.peek().value == ",":
                            self.next()
                            continue
                        break
                self.expect(")")
                expr = ("call", expr[1], None, args)
            else:
                return expr


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------


class AttrBag(dict):
    """Dict allowing CEL member access (``bag.type``)."""


def _eval(node, env):
    op = node[0]
    if op == "lit":
        return node[1]
    if op == "var":
        if node[1] not in env:
            raise CELError(f"unknown identifier {node[1]!r}")
        return env[node[1]]
    if op == "list":
        return [_eval(x, env) for x in node[1]]
    if op == "!":
        return not _truthy(_eval(node[1], env))
    if op == "neg":
        try:
            return -_eval(node[1], env)
        except TypeError as exc:
            # Same CELError conversion the binary arithmetic ops get: a
            # type mismatch is a non-matching selector, not a crash.
            raise CELError(f"cannot negate: {exc}") from exc
    if op == "||":
        return _truthy(_eval(node[1], env)) or _truthy(_eval(node[2], env))
    if op == "&&":
        return _truthy(_eval(node[1], env)) and _truthy(_eval(node[2], env))
    if op == "?:":
        return _eval(node[2] if _truthy(_eval(node[1], env)) else node[3], env)
    if op == "member":
        obj = _eval(node[1], env)
        return _get(obj, node[2])
    if op == "index":
        obj = _eval(node[1], env)
        key = _eval(node[2], env)
        return _get(obj, key)
    if op == "call":
        return _call(node[1], node[2], [_eval(a, env) for a in node[3]], env)
    if op == "in":
        item = _eval(node[1], env)
        container = _eval(node[2], env)
        try:
            return item in container
        except TypeError as exc:
            raise CELError(f"'in' needs a list/map/string container: {exc}") from exc
    left = _eval(node[1], env)
    right = _eval(node[2], env)
    if op == "==":
        return left == right
    if op == "!=":
        return left != right
    try:
        if op == "<":
            return left < right
        if op == "<=":
            return left <= right
        if op == ">":
            return left > right
        if op == ">=":
            return left >= right
        if op == "+":
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            return left // right if isinstance(left, int) and isinstance(right, int) else left / right
        if op == "%":
            # CEL % is numeric-only; Python would silently %-format a
            # string left operand (or raise ValueError on a bad format).
            if isinstance(left, str) or isinstance(right, str):
                raise CELError("% requires numeric operands")
            return left % right
    except (TypeError, ZeroDivisionError) as exc:
        # CEL-in-k8s semantics: an evaluation error (type mismatch, division
        # by zero) makes the selector a non-match, never a crash.
        raise CELError(str(exc)) from exc
    raise CELError(f"unsupported operator {op!r}")


def _truthy(v) -> bool:
    if not isinstance(v, bool):
        raise CELError(f"expected bool, got {type(v).__name__}")
    return v


def _get(obj, key):
    if isinstance(obj, dict):
        if key not in obj:
            raise CELError(f"no such key: {key!r}")
        return obj[key]
    if isinstance(obj, (list, str)) and isinstance(key, int):
        try:
            return obj[key]
        except IndexError as exc:
            raise CELError(str(exc)) from exc
    raise CELError(f"cannot index {type(obj).__name__} with {key!r}")


def _call(name, recv_node, args, env):
    recv = _eval(recv_node, env) if recv_node is not None else None
    if name == "size":
        if recv is None and len(args) != 1:
            raise CELError(f"size() takes exactly one argument, got {len(args)}")
        target = args[0] if recv is None else recv
        if not isinstance(target, (str, list, dict)):
            raise CELError(f"size() argument must be sized, got {type(target).__name__}")
        return len(target)
    if name == "quantity" and recv is None:
        from k8s_dra_driver_tpu.kube import quantity as q

        if (
            len(args) != 1
            or isinstance(args[0], bool)  # no bool->int coercion in CEL
            or not isinstance(args[0], (str, int))
        ):
            raise CELError(f"quantity() takes one string/int argument, got {args!r}")
        try:
            return q.parse(args[0])
        except q.InvalidQuantity as exc:
            raise CELError(str(exc)) from exc
    if recv is None:
        raise CELError(f"unknown function {name!r}")
    if not isinstance(recv, str):
        raise CELError(f"{name}() receiver must be string")
    if len(args) != 1:
        raise CELError(f"{name}() takes exactly one argument, got {len(args)}")
    (arg,) = args
    if name == "matches":
        if not isinstance(arg, str):
            raise CELError("matches() argument must be string")
        _guard_regex(arg)
        try:
            return re.search(arg, recv) is not None
        except re.error as exc:
            raise CELError(f"bad regex: {exc}") from exc
    if name == "startsWith":
        return recv.startswith(arg)
    if name == "endsWith":
        return recv.endswith(arg)
    if name == "contains":
        return arg in recv
    raise CELError(f"unknown method {name!r}")


_MAX_REGEX_LEN = 256


def _guard_regex(pattern: str) -> None:
    """Reject patterns that can backtrack catastrophically.

    Real CEL mandates RE2 (linear time); Python's ``re`` backtracks, so a
    user-authored selector like ``(a+)+b`` or ``(a|a)+$`` could hang
    allocation for every claim.  Conservative static screen: a quantifier
    applied to a group whose body contains a quantifier OR an alternation
    (the two classic exponential shapes) is rejected, as are oversized
    patterns.  Character classes are skipped (literal ``+`` inside
    ``[...]`` is not a quantifier).  Legitimate device selectors
    (``v5e|v6e``, ``tpu-.*``, ``[0-9+]+`` , anchored literals) pass;
    quantified alternation groups like ``(ab|cd)+`` are rejected — a
    price of not having RE2."""
    if len(pattern) > _MAX_REGEX_LEN:
        raise CELError(f"regex longer than {_MAX_REGEX_LEN} chars")
    # per open group: does its body contain a quantifier or alternation?
    depth_danger: list[bool] = [False]
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "\\":
            i += 2
            continue
        if c == "[":
            # skip the character class: ']' is literal when first (possibly
            # after '^'), escapes respected
            j = i + 1
            if j < len(pattern) and pattern[j] == "^":
                j += 1
            if j < len(pattern) and pattern[j] == "]":
                j += 1
            while j < len(pattern) and pattern[j] != "]":
                j += 2 if pattern[j] == "\\" else 1
            i = j + 1
            continue
        if c == "(":
            depth_danger.append(False)
        elif c == ")":
            inner = depth_danger.pop() if len(depth_danger) > 1 else False
            if inner and i + 1 < len(pattern) and pattern[i + 1] in "*+{":
                raise CELError(
                    "regex rejected: quantified group containing a quantifier "
                    "or alternation (catastrophic backtracking risk; CEL "
                    "proper uses RE2)"
                )
            # a dangerous group makes the ENCLOSING group dangerous too
            if inner and depth_danger:
                depth_danger[-1] = True
        elif (
            c in "*+{|"
            or (c == "?" and i > 0 and pattern[i - 1] not in "(*+{?")
        ):
            depth_danger[-1] = True
        i += 1


class CompiledExpr:
    def __init__(self, src: str):
        self.src = src
        try:
            self.ast = _Parser(_lex(src)).parse()
        except RecursionError as exc:
            # A pathologically nested user expression must not blow the
            # interpreter stack out of the allocator (fuzz finding).
            raise CELError("expression too deeply nested") from exc

    def evaluate(self, env: dict[str, Any]) -> Any:
        """The only-CELError boundary.

        Callers (allocator._matches_selectors) treat CELError as
        "selector does not match" and anything else as a crash — so EVERY
        runtime error converts here, not just the types we have met so
        far: patching leak classes one exception at a time (TypeError,
        then ZeroDivisionError, then ValueError from str %, then
        unhashable-key TypeError...) was whack-a-mole; a user-authored
        expression must never take down allocation."""
        try:
            return _eval(self.ast, env)
        except CELError:
            raise
        except RecursionError as exc:
            raise CELError("expression too deeply nested") from exc
        except Exception as exc:
            raise CELError(f"evaluation error: {type(exc).__name__}: {exc}") from exc


# Compile cache: bounded LRU.  Selector strings are user-authored (claim
# specs) — an unbounded dict would let adversarial or generated selectors
# grow allocator memory without limit.  1024 entries comfortably covers a
# cluster's distinct DeviceClass + request selectors while capping worst
# case at ~1k parsed ASTs.
_CACHE_CAPACITY = 1024
_cache: "OrderedDict[str, CompiledExpr]" = OrderedDict()
_cache_lock = threading.Lock()


def compile_expr(src: str) -> CompiledExpr:
    with _cache_lock:
        compiled = _cache.get(src)
        if compiled is not None:
            _cache.move_to_end(src)
            return compiled
    compiled = CompiledExpr(src)  # parse outside the lock: may raise CELError
    with _cache_lock:
        _cache[src] = compiled
        _cache.move_to_end(src)
        while len(_cache) > _CACHE_CAPACITY:
            _cache.popitem(last=False)
    return compiled


def evaluate(src: str, env: dict[str, Any]) -> Any:
    return compile_expr(src).evaluate(env)
