"""kube-scheduler extender: topology-aware DRA filtering over HTTP.

SURVEY.md §3.5 names the boundary this service exists for: the upstream
scheduler allocates per-claim via CEL + capacity markers, so any TPU
geometry policy must be expressible as published device attributes —
*"unless we also ship a scheduler extender."*  This is that extender.  It
wires the repo's structured allocator (`scheduler/allocator.py` — the full
backtracking search with subslice overlap markers and matchAttribute
constraints) behind the upstream scheduler-extender webhook protocol, so a
cluster whose geometry outgrows CEL (multi-claim bin packing, cross-node
tightness policy) can delegate:

* ``POST /filter`` — for each candidate node, dry-run every one of the
  pod's ResourceClaims (`Allocator.plan`, no write); nodes where any claim
  is unsatisfiable land in ``failedNodes`` with the allocator's reason.
* ``POST /prioritize`` — score feasible nodes 0..10 by the weighted
  multi-objective :class:`~k8s_dra_driver_tpu.scheduler.objectives.PlanScore`
  (packing tightness, remaining-geometry fragmentation, stranding risk,
  power, spread — weights from ``DRA_SCORE_WEIGHTS``).  ``PlanScore.total``
  is in [0, 1], so ``round(MAX_PRIORITY * total)`` stays on the upstream
  0..10 wire contract.  Scoring failures are journaled per node and counted
  (``dra_extender_score_errors_total``) instead of silently zeroing.
* ``POST /bind`` — commit: allocate all claims, reserve them for the pod,
  then bind the pod to the node; every step is compensated on failure
  (deallocate/unreserve in reverse) so a lost race leaves no partial state.

Wire format: the upstream ``k8s.io/kube-scheduler/extender/v1`` JSON
shapes re-authored field-for-field (ExtenderArgs ``pod``/``nodes``/
``nodenames``; ExtenderFilterResult ``nodenames``/``failedNodes``/
``error``; HostPriority ``host``/``score``; ExtenderBindingArgs
``podName``/``podNamespace``/``podUID``/``node``) — the compatibility
surface a real kube-scheduler policy config dials
(``urlPrefix`` + ``filterVerb``/``prioritizeVerb``/``bindVerb``).

The backing API client needs only get/list/update — both the in-memory
fake (`kube/fakeserver.py`, tests/demo) and the real REST client
(`kube/restclient.py`) satisfy it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from k8s_dra_driver_tpu.e2e.harness import claim_name_for_ref
from k8s_dra_driver_tpu.kube.objects import Node, Pod, ResourceClaim
from k8s_dra_driver_tpu.scheduler import objectives
from k8s_dra_driver_tpu.scheduler.allocator import AllocationError, Allocator
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

MAX_PRIORITY = 10  # upstream extender/v1 MaxExtenderPriority

_SCORE_ERRORS = REGISTRY.counter(
    "dra_extender_score_errors_total",
    "prioritize() scoring failures that zeroed a node, by exception type",
)


class SchedulerExtender:
    """HTTP(S) scheduler-extender service over an `Allocator`.

    Exposure note: ``/bind`` mutates cluster state (allocates claims,
    reserves them, writes ``pod.spec.nodeName``) with the controller's
    credentials, so anything that can reach the Service can drive
    allocations.  Serve TLS by passing ``tls_cert``/``tls_key`` (the
    scheduler policy then sets ``enableHTTPS: true``), and restrict the
    Service to the control plane with a NetworkPolicy — see
    demo/specs/scheduler/README.md and the helm values
    ``extenderTLSSecret`` / ``extenderAllowedCIDRs``.
    """

    def __init__(self, server, allocator: Allocator | None = None,
                 port: int = 0, bind_host: str = "127.0.0.1",
                 tls_cert: str | None = None, tls_key: str | None = None,
                 weights: dict | None = None,
                 power_table: dict | None = None):
        self._server = server
        self._allocator = allocator or Allocator(server)
        # Scoring policy: explicit weights win; otherwise DRA_SCORE_WEIGHTS
        # (weights_from_env raises on a malformed spec — a typo'd production
        # knob must fail deploy, not silently revert to defaults).
        self._weights = weights if weights is not None else objectives.weights_from_env()
        self._power_table = (
            power_table if power_table is not None else dict(objectives.DEFAULT_POWER_TABLE)
        )
        self._lock = threading.Lock()  # one verb at a time: plan vs bind races
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                length = int(self.headers.get("Content-Length", 0))
                try:
                    args = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as exc:
                    self._reply(400, {"error": f"bad JSON: {exc}"})
                    return
                try:
                    if self.path == "/filter":
                        body = outer.filter(args)
                    elif self.path == "/prioritize":
                        body = outer.prioritize(args)
                    elif self.path == "/bind":
                        body = outer.bind(args)
                    else:
                        self.send_error(404)
                        return
                except Exception as exc:  # noqa: BLE001 - webhook must answer
                    # /prioritize's wire type is a JSON array; an error
                    # object would fail the scheduler-side unmarshal.
                    body = (
                        []
                        if self.path == "/prioritize"
                        else {"error": f"{type(exc).__name__}: {exc}"}
                    )
                self._reply(200, body)

            def _reply(self, code: int, body) -> None:
                payload = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *args):  # silence per-request logging
                pass

            # Bounds a stalled or malicious client: the socket timeout covers
            # the deferred TLS handshake and request reads, both of which run
            # in THIS connection's thread (see do_handshake_on_connect below).
            timeout = 30
            # Keep-alive (every reply carries Content-Length): the scheduler
            # issues /filter+/prioritize+/bind per pod per cycle, and under
            # TLS a close-per-request HTTP/1.0 server would redo the
            # handshake for each — scheduling-latency for nothing.
            protocol_version = "HTTP/1.1"

        if bool(tls_cert) != bool(tls_key):
            raise ValueError(
                "extender TLS requires BOTH tls_cert and tls_key — refusing "
                "to fail open to plain HTTP on a half-specified config"
            )
        self._httpd = ThreadingHTTPServer((bind_host, port), Handler)
        self.scheme = "http"
        if tls_cert and tls_key:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(certfile=tls_cert, keyfile=tls_key)
            # do_handshake_on_connect=False: with a wrapped LISTENING socket
            # the handshake would otherwise run inside accept() on the
            # serve_forever thread, so one client that connects and sends
            # nothing wedges every scheduler webhook call.  Deferred, it runs
            # on the per-connection handler thread under Handler.timeout.
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False,
            )
            self.scheme = "https"
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- verbs (also callable directly, e.g. from tests) -------------------

    def filter(self, args: dict) -> dict:
        """ExtenderArgs -> ExtenderFilterResult.  The reply mirrors the
        request's shape: a caller that sent full ``nodes`` (a scheduler
        without nodeCacheCapable) reads ``result.Nodes``, one that sent
        ``nodenames`` reads ``result.NodeNames`` — upstream HTTPExtender
        consults exactly one of the two."""
        pod = args.get("pod") or {}
        nodes = self._candidate_nodes(args)
        with self._lock:
            claims = self._claims_from_pod_dict(pod)
            passed, failed = [], {}
            for name, labels in nodes:
                reason = self._node_feasible(claims, name, labels)
                if reason is None:
                    passed.append(name)
                else:
                    failed[name] = reason
        out = {"nodenames": passed, "failedNodes": failed, "error": ""}
        sent_nodes = args.get("nodes")
        if sent_nodes and sent_nodes.get("items"):
            keep = set(passed)
            out["nodes"] = {
                "items": [
                    n for n in sent_nodes["items"]
                    if (n.get("metadata") or {}).get("name") in keep
                ]
            }
        return out

    def prioritize(self, args: dict) -> list[dict]:
        """ExtenderArgs -> HostPriorityList (a JSON *array* — the wire
        contract holds even on errors: any failure scores the node 0,
        because upstream HTTPExtender.Prioritize unmarshals the body into
        a HostPriorityList and would choke on an error object)."""
        pod = args.get("pod") or {}
        nodes = self._candidate_nodes(args)
        out = []
        with self._lock:
            try:
                claims = self._claims_from_pod_dict(pod)
            except Exception:  # noqa: BLE001 - e.g. claim not created yet
                return [{"host": name, "score": 0} for name, _ in nodes]
            for name, labels in nodes:
                score = 0.0
                try:
                    plans = self._joint_plans(claims, name, labels)
                    if plans:
                        score = max(
                            objectives.score_plan(
                                p,
                                weights=self._weights,
                                power_table=self._power_table,
                            ).total
                            for p in plans
                        )
                except AllocationError:
                    # Infeasible is a normal verdict (the node just loses),
                    # not a scoring failure — no error metric.
                    score = 0.0
                except Exception as exc:  # noqa: BLE001 - zero the node LOUDLY
                    _SCORE_ERRORS.inc(reason=type(exc).__name__)
                    JOURNAL.record(
                        "extender", "score.error", node=name,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                    score = 0.0
                out.append({"host": name, "score": round(MAX_PRIORITY * score)})
        return out

    def bind(self, args: dict) -> dict:
        """ExtenderBindingArgs -> ExtenderBindingResult.  Allocates +
        reserves every pod claim, then binds the pod — compensating in
        reverse on any failure (the Prepare-path rollback discipline,
        device_state.py, applied at the scheduling boundary)."""
        name = args.get("podName", "")
        namespace = args.get("podNamespace", "") or "default"
        uid = args.get("podUID", "")
        node = args.get("node", "")
        with self._lock:
            try:
                pod = self._server.get(Pod.KIND, name, namespace)
            except Exception as exc:  # noqa: BLE001
                return {"error": f"pod {namespace}/{name}: {exc}"}
            labels = self._node_labels(node)
            done: list = []  # (claim, was_unallocated) for compensation
            try:
                claims = self._pod_claims(name, namespace, pod.spec or {})
                # A shared claim allocated since filter ran pins the pod:
                # binding here would strand it away from its devices
                # (allocate's idempotent early-return can't catch this).
                pinned = self._allocation_pins_elsewhere(claims, node, labels)
                if pinned is not None:
                    return {"error": pinned}
                for claim in claims:
                    was_unallocated = claim.status.allocation is None
                    claim = self._allocator.allocate(
                        claim, node_name=node, node_labels=labels
                    )
                    claim = self._allocator.reserve(claim, pod_name=name, pod_uid=uid)
                    done.append((claim, was_unallocated))
                pod.metadata.labels["_scheduled_node"] = node
                if isinstance(pod.spec, dict):
                    pod.spec["nodeName"] = node
                self._server.update(pod)
            except Exception as exc:  # noqa: BLE001
                for claim, was_unallocated in reversed(done):
                    try:
                        current = self._server.get(
                            ResourceClaim.KIND,
                            claim.metadata.name,
                            claim.metadata.namespace,
                        )
                        current = self._allocator.unreserve(current, uid)
                        if was_unallocated and not current.status.reserved_for:
                            self._allocator.deallocate(current)
                    except Exception:  # noqa: BLE001 - best-effort unwind
                        pass
                return {"error": f"{type(exc).__name__}: {exc}"}
        return {"error": ""}

    # -- helpers -----------------------------------------------------------

    def _candidate_nodes(self, args: dict) -> list[tuple[str, dict]]:
        """(name, labels) per candidate from ExtenderArgs: full ``nodes``
        (NodeList) carry their labels; bare ``nodenames`` resolve labels
        from the API server."""
        nodes = args.get("nodes")
        if nodes and nodes.get("items"):
            return [
                (
                    (n.get("metadata") or {}).get("name", ""),
                    (n.get("metadata") or {}).get("labels") or {},
                )
                for n in nodes["items"]
            ]
        return [(n, self._node_labels(n)) for n in args.get("nodenames") or []]

    def _node_labels(self, name: str) -> dict:
        try:
            return dict(self._server.get(Node.KIND, name).metadata.labels)
        except Exception:  # noqa: BLE001 - unknown node: hostname label only
            return {}

    def _claims_from_pod_dict(self, pod: dict) -> list:
        meta = pod.get("metadata") or {}
        return self._pod_claims(
            meta.get("name", ""),
            meta.get("namespace") or "default",
            pod.get("spec") or {},
        )

    def _pod_claims(self, name: str, namespace: str, spec: dict) -> list:
        """Resolve the pod's resourceClaims entries to ResourceClaim objects
        (template instances follow THE naming rule, harness.claim_name_for_ref)."""
        return [
            self._server.get(
                ResourceClaim.KIND, claim_name_for_ref(name, ref), namespace
            )
            for ref in spec.get("resourceClaims", [])
        ]

    def _node_feasible(self, claims: list, node: str, labels: dict) -> str | None:
        """None when every claim fits on ``node`` JOINTLY; else the first
        reason.  Already-allocated claims pass iff their allocation's node
        selector admits this node (gpu-test3 pattern: a shared claim pins
        pod 2 to pod 1's node)."""
        reason = self._allocation_pins_elsewhere(claims, node, labels)
        if reason is not None:
            return reason
        try:
            self._joint_plans(claims, node, labels)
        except AllocationError as exc:
            return str(exc)
        return None

    def _joint_plans(self, claims: list, node: str, labels: dict) -> list:
        """Plan the pod's unallocated claims as ONE placement: each plan's
        chosen devices and markers are excluded from the next search, so
        two 1-chip claims cannot both pass a node with one free chip (they
        would in isolation, and the pod would livelock at bind)."""
        plans = []
        taken_keys: set = set()
        taken_markers: set = set()
        for claim in claims:
            if claim.status.allocation is not None:
                continue
            plan = self._allocator.plan(
                claim,
                node_name=node,
                node_labels=labels,
                exclude_devices=frozenset(taken_keys),
                extra_markers=frozenset(taken_markers),
            )
            for _, c in plan.chosen:
                taken_keys.add(c.key)
                taken_markers.update(c.markers)
            plans.append(plan)
        return plans

    @staticmethod
    def _allocation_pins_elsewhere(claims: list, node: str, labels: dict) -> str | None:
        """Reason string when any already-allocated claim's node selector
        rejects ``node`` — shared by filter (exclude the node) and bind
        (refuse: the pod would land away from its devices)."""
        for claim in claims:
            if claim.status.allocation is None:
                continue
            sel = claim.status.allocation.node_selector
            if sel is not None and not sel.matches(
                {"kubernetes.io/hostname": node, **labels}
            ):
                return f"claim {claim.metadata.name!r} already allocated elsewhere"
        return None
