"""TPU-native Kubernetes Dynamic Resource Allocation (DRA) driver.

A from-scratch re-design of the capabilities of NVIDIA/k8s-dra-driver for TPU
hardware (reference layer map: SURVEY.md §1).  The package splits the same way
the reference does — a config API carried opaquely inside ResourceClaims, a
node-local kubelet plugin, and a cluster-scoped controller — but the internals
are TPU-idiomatic: chip enumeration through a C++ ``libtpuinfo`` shim over
``/dev/accel*`` (instead of NVML cgo), MIG-profile partitioning becomes ICI
subslice-shape geometry, and IMEX-channel pools become multi-host slice
membership with JAX/libtpu environment injection.
"""

from k8s_dra_driver_tpu.version import __version__

DRIVER_NAME = "tpu.google.com"
"""DNS-style driver name used in DeviceClasses, ResourceSlices and CDI kinds.

Mirrors the role of ``gpu.nvidia.com`` in the reference
(cmd/nvidia-dra-plugin/main.go:36-42).
"""

__all__ = ["DRIVER_NAME", "__version__"]
