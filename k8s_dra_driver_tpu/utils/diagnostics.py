"""HTTP diagnostics endpoint: /metrics, /healthz, /debug/state.

Mirror of the controller's SetupHTTPEndpoint (cmd/nvidia-dra-controller/
main.go:194-241, promhttp + pprof), extended to both binaries — the
reference's plugin has no diagnostics at all (SURVEY.md §5)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from k8s_dra_driver_tpu.utils.metrics import REGISTRY, Registry
from k8s_dra_driver_tpu.utils.tracing import TRACER


class DiagnosticsServer:
    def __init__(
        self,
        port: int = 0,
        registry: Registry = REGISTRY,
        state_provider: Optional[Callable[[], dict]] = None,
        bind_host: str = "0.0.0.0",
    ):
        """``bind_host`` defaults to all interfaces so in-cluster scrapes and
        kubelet probes (which hit the pod IP) can reach the endpoint."""
        registry_ref = registry
        state_ref = state_provider or (lambda: {})

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = registry_ref.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path == "/healthz":
                    body = b"ok"
                    ctype = "text/plain"
                elif self.path == "/debug/state":
                    body = json.dumps(state_ref(), indent=1, default=str).encode()
                    ctype = "application/json"
                elif self.path == "/debug/traces":
                    body = json.dumps(TRACER.recent(), indent=1, default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request logging
                pass

        self._httpd = ThreadingHTTPServer((bind_host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
