"""HTTP diagnostics endpoint: /metrics, /healthz, /debug/*.

Mirror of the controller's SetupHTTPEndpoint (cmd/nvidia-dra-controller/
main.go:194-241, promhttp + pprof), extended to both binaries — the
reference's plugin has no diagnostics at all (SURVEY.md §5).

Endpoints (ARCHITECTURE.md "Observability" documents the inventory):

* ``/metrics``        — Prometheus text exposition of the process registry
* ``/healthz``        — liveness: ``ok``
* ``/debug/state``    — the owner's ``state_provider()`` snapshot (JSON)
* ``/debug/traces``   — the tracer ring's recent spans (JSON)
* ``/debug/journal``  — the flight recorder's tail (JSON); filters:
  ``?limit=N&correlation=<id>&component=<name>``
* ``/debug/stacks``   — every Python thread's stack (JSON) — what
  tools/diag_bundle.py pulls to bundle a LIVE process without attaching
  a debugger
* ``/debug/serve``    — per-engine ``EngineStats`` snapshots plus recent
  request traces from every live serving engine in the process (JSON);
  filters: ``?request_id=N`` (full timeline for one correlation id) and
  ``?limit=N`` (recent-trace ring depth).  This is the fleet
  load-signal contract: a router scrapes it to weigh replicas.
* ``/debug/fleet``    — every live :class:`~k8s_dra_driver_tpu.models.
  fleet.FleetRouter`'s view: per-replica health state (healthy/suspect/
  evacuating/drained), breaker state, last verdict and cached
  ``EngineStats``, plus the fleet front-door queue depth and parked
  evacuees (JSON).
* ``/debug/disagg``   — every live :class:`~k8s_dra_driver_tpu.models.
  disagg.DisaggRouter`'s view: prefill/decode pool membership (full
  fleet stats per pool), staged handoffs, in-flight transfers and the
  channel's claim/budget/outcome tally (JSON).
* ``/debug/transport`` — every live :class:`~k8s_dra_driver_tpu.models.
  transport.TransportChannel`'s view: the link's breaker state and
  cooldown, liveness (pong age, RTT), reconnect count, reclaimed-stream
  count and the channel's claim/budget/outcome tally — plus every live
  :class:`~k8s_dra_driver_tpu.models.transport.RemotePool`'s pending/
  resident/failed stream counts (JSON).
* ``/debug/autoscale`` — every live :class:`~k8s_dra_driver_tpu.models.
  autoscaler.FleetAutoscaler`'s view: policy thresholds, vote streaks,
  pending spawns, SLO attainment window and the latest decision doc
  (JSON).
* ``/debug/fleet-journal`` — the observability plane's merged,
  instance-tagged journal: every federated worker's flight-recorder
  tail interleaved with the local process's, ordered by event
  timestamp (JSON); filters: ``?limit=N&correlation=<id>&
  component=<name>&instance=<worker>``.
* ``/debug/fleet-traces`` — merged cross-process span trees: every
  federated worker's spans skew-normalized into the control plane's
  monotonic domain and joined with local spans by trace/span/parent
  ids (JSON); filters: ``?trace_id=<id>&limit=N``.

``/metrics`` federates automatically: when the observability plane
(models/obs_plane.py) is loaded and has ingested TELEM snapshots, the
local render is followed by every worker's registry rewritten under
its ``instance=`` label.  Without the plane loaded the endpoint is
byte-identical to the plain local render — control-plane binaries pay
nothing for the feature they don't use.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from k8s_dra_driver_tpu.utils.journal import JOURNAL, Journal
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, Registry
from k8s_dra_driver_tpu.utils.tracing import TRACER


class DiagnosticsServer:
    def __init__(
        self,
        port: int = 0,
        registry: Registry = REGISTRY,
        state_provider: Optional[Callable[[], dict]] = None,
        bind_host: str = "0.0.0.0",
        journal: Journal = JOURNAL,
    ):
        """``bind_host`` defaults to all interfaces so in-cluster scrapes and
        kubelet probes (which hit the pod IP) can reach the endpoint."""
        registry_ref = registry
        state_ref = state_provider or (lambda: {})
        journal_ref = journal

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                url = urllib.parse.urlsplit(self.path)
                query = urllib.parse.parse_qs(url.query)
                if url.path == "/metrics":
                    # Federate only when the obs plane is ALREADY loaded:
                    # importing it here would drag models/ into
                    # control-plane binaries that never use federation.
                    import sys

                    obs = sys.modules.get("k8s_dra_driver_tpu.models.obs_plane")
                    if obs is not None and obs.FLEET.stats()["instances"]:
                        text = obs.FLEET.render_federated(registry_ref)
                    else:
                        text = registry_ref.render()
                    body = text.encode()
                    ctype = "text/plain; version=0.0.4"
                elif url.path == "/healthz":
                    body = b"ok"
                    ctype = "text/plain"
                elif url.path == "/debug/state":
                    body = json.dumps(state_ref(), indent=1, default=str).encode()
                    ctype = "application/json"
                elif url.path == "/debug/traces":
                    body = json.dumps(TRACER.recent(), indent=1, default=str).encode()
                    ctype = "application/json"
                elif url.path == "/debug/journal":
                    try:
                        limit = int(query.get("limit", ["200"])[0])
                    except ValueError:
                        limit = 200
                    doc = {
                        **journal_ref.stats(),
                        "events": journal_ref.tail(
                            limit=limit,
                            correlation=query.get("correlation", [None])[0],
                            component=query.get("component", [None])[0],
                        ),
                    }
                    body = json.dumps(doc, indent=1, default=str).encode()
                    ctype = "application/json"
                elif url.path == "/debug/stacks":
                    from k8s_dra_driver_tpu.utils.watchdog import thread_stacks

                    body = json.dumps(thread_stacks(), indent=1).encode()
                    ctype = "application/json"
                elif url.path == "/debug/serve":
                    # Imported lazily: diagnostics serves control-plane
                    # binaries that never load the models package.
                    from k8s_dra_driver_tpu.models.telemetry import debug_serve_doc

                    try:
                        rid = int(query.get("request_id", [""])[0])
                    except ValueError:
                        rid = None
                    try:
                        limit = int(query.get("limit", ["8"])[0])
                    except ValueError:
                        limit = 8
                    doc = debug_serve_doc(request_id=rid, trace_limit=limit)
                    body = json.dumps(doc, indent=1, default=str).encode()
                    ctype = "application/json"
                elif url.path == "/debug/fleet":
                    # Lazy for the same reason as /debug/serve; fleet.py
                    # itself never imports jax, so this stays cheap even
                    # in control-plane binaries.
                    from k8s_dra_driver_tpu.models.fleet import debug_fleet_doc

                    body = json.dumps(
                        debug_fleet_doc(), indent=1, default=str
                    ).encode()
                    ctype = "application/json"
                elif url.path == "/debug/disagg":
                    # Lazy for the same reason as /debug/fleet; disagg.py
                    # is jax-free, so this stays control-plane safe.
                    from k8s_dra_driver_tpu.models.disagg import debug_disagg_doc

                    body = json.dumps(
                        debug_disagg_doc(), indent=1, default=str
                    ).encode()
                    ctype = "application/json"
                elif url.path == "/debug/transport":
                    # Lazy for the same reason as /debug/disagg; the
                    # transport's engine imports live behind worker_main,
                    # so this stays control-plane safe.
                    from k8s_dra_driver_tpu.models.transport import (
                        debug_transport_doc,
                    )

                    body = json.dumps(
                        debug_transport_doc(), indent=1, default=str
                    ).encode()
                    ctype = "application/json"
                elif url.path == "/debug/autoscale":
                    # Lazy for the same reason as /debug/fleet; the
                    # autoscaler is jax-free host-side control law.
                    from k8s_dra_driver_tpu.models.autoscaler import (
                        debug_autoscale_doc,
                    )

                    body = json.dumps(
                        debug_autoscale_doc(), indent=1, default=str
                    ).encode()
                    ctype = "application/json"
                elif url.path == "/debug/fleet-journal":
                    # Lazy for the same reason as /debug/fleet; the obs
                    # plane imports only utils, never jax.
                    from k8s_dra_driver_tpu.models.obs_plane import FLEET

                    try:
                        limit = int(query.get("limit", ["200"])[0])
                    except ValueError:
                        limit = 200
                    doc = FLEET.fleet_journal_doc(
                        limit=limit,
                        correlation=query.get("correlation", [None])[0],
                        component=query.get("component", [None])[0],
                        instance=query.get("instance", [None])[0],
                    )
                    body = json.dumps(doc, indent=1, default=str).encode()
                    ctype = "application/json"
                elif url.path == "/debug/fleet-traces":
                    from k8s_dra_driver_tpu.models.obs_plane import FLEET

                    try:
                        limit = int(query.get("limit", ["50"])[0])
                    except ValueError:
                        limit = 50
                    doc = FLEET.fleet_traces_doc(
                        trace_id=query.get("trace_id", [None])[0],
                        limit=limit,
                    )
                    body = json.dumps(doc, indent=1, default=str).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # silence per-request logging
                pass

        self._httpd = ThreadingHTTPServer((bind_host, port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
