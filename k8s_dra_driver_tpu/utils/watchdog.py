"""Stall watchdog + one-shot diag bundles.

BENCH_r05.json: the data plane timed out after 240s with nothing but a
guess ("hung device link?") and a failed 900s backend probe.  This module
makes the next hang diagnosable from a single artifact:

* :class:`Watchdog` — heartbeat-armed guards wrapping data-plane sections
  (collective launches, decode steps, the topology-daemon poll loop).  A
  guard arms when entered; code inside calls :meth:`Guard.beat` on
  progress; a monitor thread (or an explicit :meth:`Watchdog.check_now`
  for deterministic tests) declares a stall when a guard goes
  ``timeout_s`` without a heartbeat and dumps a diag bundle.

* :func:`dump_diag_bundle` — the one-shot snapshot: **all Python thread
  stacks**, the journal tail (utils/journal.py), the tracer ring
  (utils/tracing.py), ``/debug/state``, and the rendered metrics, written
  as one JSON file.  Used by the watchdog on stall, by bench.py's
  data-plane-timeout path, and (over HTTP) by tools/diag_bundle.py — the
  ``nvidia-bug-report.sh`` analogue.

A hung jax dispatch cannot heartbeat — that is the point: the guard's
arm-time metadata (section name, correlation id, age) is exactly what the
bundle needs to say *what* was in flight when the link died.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from dataclasses import dataclass, field
from pathlib import Path

from k8s_dra_driver_tpu.utils.journal import JOURNAL, Journal
from k8s_dra_driver_tpu.utils.logging import get_logger
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from k8s_dra_driver_tpu.utils.tracing import TRACER

log = get_logger("tpu-dra-watchdog")

# Data-plane sections default to this stall budget; override per guard or
# via TPU_DRA_WATCHDOG_TIMEOUT_S (the bench raises it for cold compiles).
DEFAULT_TIMEOUT_S = 300.0


def thread_stacks() -> dict[str, list[str]]:
    """Every live Python thread's stack, keyed ``"name (tid)"`` — the
    in-process py-spy that tells a post-mortem WHERE each thread sat."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: dict[str, list[str]] = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} ({tid})"
        out[label] = [ln.rstrip("\n") for ln in traceback.format_stack(frame)]
    return out


def dump_diag_bundle(
    bundle_dir: str,
    reason: str,
    correlation: str = "",
    state: dict | None = None,
    journal: Journal = JOURNAL,
    extra: dict | None = None,
) -> str:
    """Write one self-contained JSON diag bundle and return its path.

    Best-effort by design: a section that itself raises (a state provider
    touching a wedged lock, say) becomes an ``"error: ..."`` string in the
    bundle rather than suppressing the artifact — a diagnostics path must
    never be the second thing that breaks.
    """

    def guarded(fn):
        try:
            return fn()
        except Exception as exc:  # noqa: BLE001 - bundle must still land
            return f"error: {type(exc).__name__}: {exc}"

    bundle = {
        "kind": "tpu-dra-diag-bundle",
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reason": reason,
        **({"correlation": correlation} if correlation else {}),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "thread_stacks": guarded(thread_stacks),
        "journal_tail": guarded(lambda: journal.tail(limit=500)),
        "journal_stats": guarded(journal.stats),
        "traces": guarded(TRACER.recent),
        "state": guarded(lambda: state if state is not None else {}),
        "metrics": guarded(REGISTRY.render),
        **(extra or {}),
    }
    path = Path(bundle_dir)
    path.mkdir(parents=True, exist_ok=True)
    out = path / f"diag-bundle-{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}-{os.getpid()}.json"
    out.write_text(json.dumps(bundle, indent=1, default=str))
    journal.record(
        "watchdog", "bundle.written", correlation=correlation,
        path=str(out), reason=reason,
    )
    return str(out)


@dataclass
class Guard:
    """One armed data-plane section.  ``beat()`` on progress; the section
    is healthy while ``now - last_beat < timeout_s``."""

    name: str
    timeout_s: float
    correlation: str = ""
    armed_at: float = field(default_factory=time.monotonic)
    last_beat: float = field(init=False)
    stalled: bool = field(init=False, default=False)

    def __post_init__(self):
        self.last_beat = self.armed_at

    def beat(self) -> None:
        self.last_beat = time.monotonic()
        # A late heartbeat after a stall verdict means the section was
        # slow, not dead; clear the flag so one guard can't spam bundles.
        self.stalled = False

    def age_s(self, now: float | None = None) -> float:
        return (now if now is not None else time.monotonic()) - self.last_beat

    def to_json(self, now: float | None = None) -> dict:
        now = now if now is not None else time.monotonic()
        return {
            "name": self.name,
            "correlation": self.correlation,
            "timeout_s": self.timeout_s,
            "armed_for_s": round(now - self.armed_at, 3),
            "since_last_beat_s": round(self.age_s(now), 3),
            "stalled": self.stalled,
        }


class Watchdog:
    """Registry of armed guards + the monitor that turns a missed
    heartbeat into a diag bundle.

    The monitor thread starts lazily on the first armed guard and polls at
    ``poll_interval_s``; tests drive :meth:`check_now` directly instead of
    racing a thread.  One bundle per stall verdict: a guard that keeps
    missing beats stays ``stalled`` and is not re-dumped until it beats
    again (or re-arms).
    """

    def __init__(
        self,
        bundle_dir: str | None = None,
        poll_interval_s: float = 1.0,
        state_provider=None,
        journal: Journal = JOURNAL,
    ):
        self._lock = threading.Lock()
        self._guards: dict[int, Guard] = {}
        self._next_id = 0
        self._journal = journal
        self._state_provider = state_provider
        self._poll_interval_s = poll_interval_s
        self._bundle_dir = bundle_dir
        self._monitor: threading.Thread | None = None
        self._stop = threading.Event()
        self._stalls = REGISTRY.counter(
            "dra_watchdog_stalls_total", "Guarded sections that missed heartbeats"
        )
        self.bundles: list[str] = []  # paths written, newest last

    @property
    def bundle_dir(self) -> str:
        return (
            self._bundle_dir
            or os.environ.get("TPU_DRA_DIAG_DIR", "")
            or str(Path(os.environ.get("TMPDIR", "/tmp")) / "tpu-dra-diag")
        )

    # -- guard lifecycle ----------------------------------------------------

    def guard(self, name: str, timeout_s: float | None = None, correlation: str = ""):
        """Context manager arming one section:

        >>> with WATCHDOG.guard("collectives.psum", 300, correlation=dev) as g:
        ...     for chunk in work:
        ...         launch(chunk)
        ...         g.beat()
        """
        if timeout_s is None:
            timeout_s = float(
                os.environ.get("TPU_DRA_WATCHDOG_TIMEOUT_S", DEFAULT_TIMEOUT_S)
            )
        return _GuardContext(self, name, timeout_s, correlation)

    def _register(self, g: Guard) -> int:
        with self._lock:
            gid = self._next_id
            self._next_id += 1
            self._guards[gid] = g
        self._ensure_monitor()
        return gid

    def _unregister(self, gid: int) -> None:
        with self._lock:
            self._guards.pop(gid, None)

    def active(self) -> list[dict]:
        now = time.monotonic()
        with self._lock:
            return [g.to_json(now) for g in self._guards.values()]

    # -- stall detection ----------------------------------------------------

    def check_now(self) -> list[str]:
        """One monitor pass; returns bundle paths written this pass.
        Tests call this directly for a deterministic verdict."""
        now = time.monotonic()
        with self._lock:
            newly_stalled = []
            for g in self._guards.values():
                if not g.stalled and g.age_s(now) >= g.timeout_s:
                    g.stalled = True
                    newly_stalled.append(g)
        written = []
        for g in newly_stalled:
            self._stalls.inc(section=g.name)
            self._journal.record(
                "watchdog", "stall.detected", correlation=g.correlation,
                section=g.name, since_last_beat_s=round(g.age_s(now), 3),
                timeout_s=g.timeout_s,
            )
            log.error(
                "watchdog: section %r stalled (%.1fs without a heartbeat, "
                "budget %.1fs, correlation %r); dumping diag bundle",
                g.name, g.age_s(now), g.timeout_s, g.correlation,
            )
            # The provider is guarded separately: a wedged owner (whose
            # stall this IS) must not cost us the bundle.
            try:
                state = self._state_provider() if self._state_provider else {}
            except Exception as exc:  # noqa: BLE001
                state = {"state_provider_error": f"{type(exc).__name__}: {exc}"}
            try:
                state = {"watchdog_guards": self.active(), **(state or {})}
                path = dump_diag_bundle(
                    self.bundle_dir,
                    reason=f"stall in {g.name}: {g.age_s(now):.1f}s without a "
                    f"heartbeat (budget {g.timeout_s:.1f}s)",
                    correlation=g.correlation,
                    state=state,
                    journal=self._journal,
                )
                self.bundles.append(path)
                written.append(path)
            except Exception as exc:  # noqa: BLE001 - detection must outlive dump
                log.error("watchdog: bundle write failed: %s", exc)
        return written

    # -- monitor thread -----------------------------------------------------

    def _ensure_monitor(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._stop.clear()
            self._monitor = threading.Thread(
                target=self._run, daemon=True, name="tpu-dra-watchdog"
            )
            self._monitor.start()

    def _run(self) -> None:
        while not self._stop.wait(self._poll_interval_s):
            with self._lock:
                idle = not self._guards
            if idle:
                continue
            self.check_now()

    def stop(self) -> None:
        self._stop.set()  # Event is self-synchronized; no lock needed
        with self._lock:  # _monitor is written under the lock in _ensure_monitor
            monitor = self._monitor
        if monitor is not None:
            monitor.join(timeout=5)


class _GuardContext:
    def __init__(self, wd: Watchdog, name: str, timeout_s: float, correlation: str):
        self._wd = wd
        self._g = Guard(name=name, timeout_s=timeout_s, correlation=correlation)
        self._gid: int | None = None

    def __enter__(self) -> Guard:
        self._gid = self._wd._register(self._g)
        return self._g

    def __exit__(self, *exc) -> None:
        if self._gid is not None:
            self._wd._unregister(self._gid)


WATCHDOG = Watchdog()
