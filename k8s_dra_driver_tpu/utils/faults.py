"""Fault injection for the API-server boundary — the chaos half of the
robustness subsystem (utils/retry.py is the policy half).

Nothing in the reference tree can *prove* its resilience claims: client-go
is trusted to relist and rate-limit, and no test ever makes the API server
misbehave.  This module makes misbehavior a first-class, deterministic test
input.  A :class:`FaultInjector` is armed with :class:`FaultProfile`\\ s and
hooked into two layers:

* ``kube.fakeserver.InMemoryAPIServer`` consults :meth:`before` ahead of
  every verb — injected 5xx/429 errors, 409 conflicts and added latency
  reach both in-process harness traffic and (because ``e2e.mock_api``
  routes through the same store) real HTTP traffic.
* ``e2e.mock_api.MockKubeAPI`` consults the HTTP-only hooks — connection
  drops (truncated response body → ``IncompleteRead`` client-side), 410 on
  watch connect, ERROR frames mid-stream, and silent watch hangs.

Faults are injected *before* the store mutates, so an injected failure
never half-applies an operation: exactly the failure mode a client retry
must heal.  Decisions are drawn from a seeded RNG — a chaos test that
fails replays identically from its seed.

Arming: programmatic (``injector.arm(FaultProfile(...))``) or via the
``DRA_FAULTS`` env var (``error_rate=0.3,latency_ms=5,seed=7``), which
``InMemoryAPIServer`` picks up automatically so any harness/bench run can
be put under chaos without code changes.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_INJECTED = REGISTRY.counter(
    "dra_faults_injected_total", "Faults injected, by profile and fault type"
)

ENV_VAR = "DRA_FAULTS"


class StepFault(RuntimeError):
    """Injected engine-step exception attributable to ONE slot — the
    fault shape the serving quarantine path must heal (retire the slot,
    replay the burst without it).  Raised by
    :meth:`FaultInjector.maybe_raise_step` BEFORE the step dispatches, so
    no engine state has mutated when it fires."""

    def __init__(self, slot: int, message: str):
        super().__init__(message)
        self.slot = slot


class SpawnFault(RuntimeError):
    """Injected replica-factory failure — the fault shape the autoscaler's
    scale-up path must degrade under gracefully (journal the failure,
    back off, keep serving on the replicas it has).  Raised by
    :meth:`FaultInjector.maybe_fail_spawn` BEFORE the factory runs, so a
    failed spawn never leaves a half-registered replica."""

    def __init__(self, attempt: int, message: str):
        super().__init__(message)
        self.attempt = attempt


class ReplicaCrash(RuntimeError):
    """Injected whole-replica death attributable to ONE fleet replica —
    the fault shape the fleet router's evacuation path must heal (trip
    the replica's breaker, snapshot its batch, restore onto survivors).
    Raised by :meth:`FaultInjector.maybe_crash_replica` BEFORE the
    replica's burst dispatches, so the replica's host-side state is
    still consistent when the router snapshots it — the same
    pre-mutation discipline as :class:`StepFault`."""

    def __init__(self, replica: int, message: str):
        super().__init__(message)
        self.replica = replica


@dataclass
class FaultProfile:
    """One armed fault source.  Rates are probabilities per matching
    operation; ``watch_*`` counts are storm budgets consumed one per
    injection; ``limit`` caps total injections from this profile
    (0 = unlimited).  Empty ``verbs``/``kinds`` match everything.

    The ``nan_logits_rate`` / ``step_raise_rate`` / ``step_latency_s``
    fields are ENGINE-scoped (data plane): consulted by the serving
    engines once per (slot, step) ahead of every decode dispatch — before
    any device state mutates, so a quarantine replay stays safe.  They
    scope by ``slots``/``steps`` instead of verbs/kinds."""

    name: str = "fault"
    error_rate: float = 0.0  # probability of an injected APIError
    error_code: int = 500
    conflict_rate: float = 0.0  # probability of an injected 409 Conflict
    latency_s: float = 0.0  # added to every matching operation
    drop_rate: float = 0.0  # probability of a truncated HTTP response
    watch_gone: int = 0  # next N watch connects answer 410 Gone
    watch_error_frames: int = 0  # next N streams get an ERROR frame
    watch_hangs: int = 0  # next N streams stall silently...
    watch_hang_s: float = 0.0  # ...for this long before resuming
    verbs: tuple = ()  # e.g. ("PUT",); empty = all verbs
    kinds: tuple = ()  # e.g. ("ResourceSlice",); empty = all kinds
    # engine-scoped (serving data plane) kinds:
    nan_logits_rate: float = 0.0  # probability a slot's logits go NaN
    step_raise_rate: float = 0.0  # probability of a StepFault pre-dispatch
    step_latency_s: float = 0.0  # added to every matching engine step
    slots: tuple = ()  # e.g. (1, 3); empty = all slots
    steps: tuple = ()  # e.g. (5,); empty = all engine steps
    # replica-scoped (fleet router) kinds: consulted by the FleetRouter
    # once per (replica, tick) ahead of driving that replica's burst —
    # before any engine state mutates, so evacuation replay stays safe.
    # They scope by ``replicas``/``steps`` (steps = router ticks).
    replica_crash_rate: float = 0.0  # probability a replica dies (ReplicaCrash)
    replica_wedge_rate: float = 0.0  # probability a replica hangs this tick
    stats_stale_rate: float = 0.0  # probability stats() serves a frozen copy
    replicas: tuple = ()  # e.g. (1,); empty = all replicas
    # autoscaler-scoped (fleet controller) kinds: consulted by the
    # FleetAutoscaler once per scale-up attempt, BEFORE the replica
    # factory runs — a failed or stalled spawn never half-registers a
    # replica.  Spawn latency is ACCOUNTED (the pending spawn completes
    # later on the sim/monotonic clock), never slept, so chaos stays fast.
    spawn_fail_rate: float = 0.0  # probability a replica spawn errors
    spawn_latency_s: float = 0.0  # simulated seconds before a spawn is ready
    # channel-scoped (disaggregated KV handoff) kinds: consulted by the
    # HandoffChannel once per transfer, BEFORE the payload is delivered to
    # the decode pool — a dropped or corrupted transfer therefore never
    # half-installs KV bytes; the router falls back to re-prefill.
    handoff_drop_rate: float = 0.0  # probability a transfer is dropped in flight
    handoff_latency_s: float = 0.0  # simulated seconds added per transfer
    handoff_corrupt_rate: float = 0.0  # probability payload bytes arrive corrupted
    # link-scoped (multi-channel failover) kinds: consulted by the
    # ChannelSet per link consult.  ``channel_down`` kills a scoped link —
    # mid-transfer, the set must fail the hop over to a sibling link;
    # ``channel_degrade`` multiplies a scoped link's bandwidth (brownout:
    # transfers slide toward the deadline bound).  Scope by ``channels``
    # (link names); the shared ``limit`` budget caps both.
    channel_down_rate: float = 0.0  # probability a scoped link dies this consult
    channel_degrade: float = 0.0  # bandwidth multiplier (0 < f <= 1) when armed
    channels: tuple = ()  # e.g. ("ici-1",); empty = all links
    # socket-scoped (models/transport.py) kinds: consulted at the
    # transport's send/recv seams, so the in-process chaos suite covers
    # truncated frames, peer resets, slow links and silent hangs without
    # real sockets.  Latency is ACCOUNTED into the transfer's deadline
    # arithmetic (never slept); ``peer_hang`` is a storm budget — the next
    # N receiver polls process nothing, so heartbeats go unanswered.
    sock_truncate_rate: float = 0.0  # probability a sent frame is cut mid-body
    sock_reset_rate: float = 0.0  # probability the peer resets mid-transfer
    sock_latency_s: float = 0.0  # simulated seconds added per frame
    peer_hang: int = 0  # next N receiver polls stall silently
    # ``sock_partition`` is the one-way network partition: a matching
    # SENT frame is silently dropped — nothing arrives, the connection
    # stays open, and the OTHER direction keeps flowing.  Scope the
    # direction by arming it on the side whose sends should vanish, the
    # victims by ``peers`` (peer names), the window by ``steps`` (the
    # sender's per-conn frame counter); the shared ``limit`` budget
    # bounds the partition so every run eventually heals.
    sock_partition_rate: float = 0.0  # probability a sent frame vanishes
    peers: tuple = ()  # e.g. ("decode-w",); empty = all peers
    # scheduler-scoped (multi-scheduler contention harness) kinds:
    # consulted by the ContentionSim once per commit attempt, BEFORE the
    # status write is issued.  ``sched_conflict_rate`` injects a 409 at
    # the commit seam — a seeded 409 storm independent of (and on top of)
    # genuine resourceVersion CAS races; ``sched_commit_latency_s`` sleeps
    # there, widening the plan-to-commit window so real races get more
    # likely.  Scope by ``schedulers`` (worker indexes); the shared
    # ``limit`` budget caps both, so an adversarial profile that pins one
    # scheduler eventually exhausts and the run still converges.
    sched_conflict_rate: float = 0.0  # probability a commit attempt 409s
    sched_commit_latency_s: float = 0.0  # seconds slept before each commit
    schedulers: tuple = ()  # e.g. (0,); empty = all schedulers
    limit: int = 0  # total-injection cap, 0 = unlimited
    injected: int = field(default=0, compare=False)


class FaultInjector:
    """Deterministic, thread-safe fault source shared by the in-memory
    store and the HTTP facade."""

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._profiles: list[FaultProfile] = []
        self._counts: dict[str, int] = {}

    # -- arming ------------------------------------------------------------

    def arm(self, profile: FaultProfile) -> FaultProfile:
        with self._lock:
            self._profiles.append(profile)
        JOURNAL.record(
            "faults", "profile.arm", correlation=profile.name,
            error_rate=profile.error_rate, conflict_rate=profile.conflict_rate,
            drop_rate=profile.drop_rate, watch_gone=profile.watch_gone,
        )
        return profile

    def disarm(self, name: str | None = None) -> None:
        with self._lock:
            if name is None:
                self._profiles.clear()
            else:
                self._profiles = [p for p in self._profiles if p.name != name]
        JOURNAL.record("faults", "profile.disarm", correlation=name or "*")

    # -- decision points ---------------------------------------------------

    def before(self, verb: str, kind: str) -> None:
        """Server-side hook, called ahead of every store operation.  May
        sleep (latency) and may raise an injected APIError/Conflict."""
        from k8s_dra_driver_tpu.kube.fakeserver import APIError, Conflict

        for p in self._matching(verb, kind):
            if p.latency_s > 0:
                time.sleep(p.latency_s)
            if p.conflict_rate and self._roll(p, p.conflict_rate, "conflict", verb, kind):
                raise Conflict(f"fault injected by profile {p.name!r}")
            if p.error_rate and self._roll(p, p.error_rate, "error", verb, kind):
                raise APIError(p.error_code, f"fault injected by profile {p.name!r}")

    def before_sched_commit(self, scheduler: int) -> None:
        """Scheduler hook: consulted by the contention harness once per
        commit attempt, before the claim-status write goes to the store.
        Sleeps the scoped commit latency (budget-accounted, same shape as
        :meth:`take_step_latency`) and may raise an injected 409 Conflict
        attributable to the profile — the seeded storm the contention
        acceptance run converges under."""
        from k8s_dra_driver_tpu.kube.fakeserver import Conflict

        for p in self._matching_sched(scheduler):
            if p.sched_commit_latency_s > 0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(p, "sched_commit_latency", "PUT", "scheduler")
                time.sleep(p.sched_commit_latency_s)
            if p.sched_conflict_rate and self._roll(
                p, p.sched_conflict_rate, "sched_conflict", "PUT", "scheduler"
            ):
                raise Conflict(
                    f"fault injected by profile {p.name!r} "
                    f"(scheduler {scheduler})"
                )

    def take_drop(self, verb: str, kind: str) -> bool:
        """HTTP-only: should this response be truncated mid-body?"""
        for p in self._matching(verb, kind):
            if p.drop_rate and self._roll(p, p.drop_rate, "drop", verb, kind):
                return True
        return False

    def take_watch_gone(self, kind: str) -> bool:
        """HTTP-only: should this watch connect be answered 410 Gone?"""
        return self._take_counted(kind, "watch_gone")

    def take_watch_error_frame(self, kind: str) -> bool:
        """HTTP-only: should this stream get an ERROR frame and close?"""
        return self._take_counted(kind, "watch_error_frames")

    def take_watch_hang(self, kind: str) -> float:
        """HTTP-only: seconds this stream should stall silently (0 = none)."""
        for p in self._matching("GET", kind):
            with self._lock:
                if p.watch_hangs > 0 and self._budget_ok(p):
                    p.watch_hangs -= 1
                    self._record(p, "watch_hang", "GET", kind)
                    return p.watch_hang_s
        return 0.0

    # -- engine decision points (serving data plane) -----------------------

    def take_step_latency(self) -> float:
        """Engine hook: added decode-step latency.  Sleeps HERE (the same
        shape as :meth:`before`'s latency arm) and returns the seconds
        slept, so engine code never carries its own sleep."""
        total = 0.0
        for p in self._matching_engine(None, None):
            if p.step_latency_s > 0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(p, "step_latency", "STEP", "engine")
                time.sleep(p.step_latency_s)
                total += p.step_latency_s
        return total

    def take_nan_logits(self, slot: int, step: int) -> bool:
        """Engine hook: should this (slot, step)'s logits be poisoned to
        NaN?  Consulted pre-dispatch; the engine threads the verdict into
        the jitted step as a poison mask (decode.poison_rows)."""
        for p in self._matching_engine(slot, step):
            if p.nan_logits_rate and self._roll(
                p, p.nan_logits_rate, "nan_logits", f"slot-{slot}", f"step-{step}"
            ):
                return True
        return False

    def maybe_raise_step(self, slot: int, step: int) -> None:
        """Engine hook: raise a :class:`StepFault` attributable to ``slot``
        for this step.  Called BEFORE the step dispatches — no state has
        mutated when it fires, so the engine can quarantine the slot and
        re-dispatch without it."""
        for p in self._matching_engine(slot, step):
            if p.step_raise_rate and self._roll(
                p, p.step_raise_rate, "step_raise", f"slot-{slot}", f"step-{step}"
            ):
                raise StepFault(
                    slot,
                    f"fault injected by profile {p.name!r} "
                    f"(slot {slot}, step {step})",
                )

    # -- replica decision points (fleet router) ----------------------------

    def maybe_crash_replica(self, replica: int, tick: int) -> None:
        """Router hook: raise a :class:`ReplicaCrash` attributable to
        ``replica`` for this router tick.  Called BEFORE the replica's
        burst dispatches — its engine state is consistent when the crash
        fires, so the router can snapshot and evacuate it."""
        for p in self._matching_replica(replica, tick):
            if p.replica_crash_rate and self._roll(
                p, p.replica_crash_rate, "replica_crash",
                f"replica-{replica}", f"tick-{tick}",
            ):
                raise ReplicaCrash(
                    replica,
                    f"fault injected by profile {p.name!r} "
                    f"(replica {replica}, tick {tick})",
                )

    def take_replica_wedge(self, replica: int, tick: int) -> bool:
        """Router hook: should this replica hang (skip its burst) this
        tick?  A wedged replica makes no progress while holding resident
        streams — the health detector must notice and evacuate."""
        for p in self._matching_replica(replica, tick):
            if p.replica_wedge_rate and self._roll(
                p, p.replica_wedge_rate, "replica_wedge",
                f"replica-{replica}", f"tick-{tick}",
            ):
                return True
        return False

    def take_stats_stale(self, replica: int, tick: int) -> bool:
        """Router hook: should this replica's ``stats()`` read be served
        from the router's stale cache instead of the live engine?  A
        frozen load signal must gate the replica (the router cannot
        confirm health), not keep attracting traffic on rosy old
        numbers."""
        for p in self._matching_replica(replica, tick):
            if p.stats_stale_rate and self._roll(
                p, p.stats_stale_rate, "stats_stale",
                f"replica-{replica}", f"tick-{tick}",
            ):
                return True
        return False

    # -- autoscaler decision points (fleet controller) ---------------------

    def maybe_fail_spawn(self, attempt: int) -> None:
        """Autoscaler hook: raise a :class:`SpawnFault` for this scale-up
        attempt.  Called BEFORE the replica factory runs, so a failed
        spawn leaves no half-registered replica — the autoscaler journals
        the failure, backs off, and keeps serving on what it has.
        Scoped by ``steps`` (= spawn attempt numbers), so a spec can fail
        exactly the first N attempts."""
        for p in self._matching_engine(None, attempt):
            if p.spawn_fail_rate and self._roll(
                p, p.spawn_fail_rate, "spawn_fail",
                f"spawn-{attempt}", "autoscaler",
            ):
                raise SpawnFault(
                    attempt,
                    f"fault injected by profile {p.name!r} "
                    f"(spawn attempt {attempt})",
                )

    def take_spawn_latency(self, attempt: int) -> float:
        """Autoscaler hook: simulated seconds before this spawn is ready.
        Like :meth:`take_handoff_latency` it does NOT sleep — the
        autoscaler parks the spawn as pending and realizes it once the
        clock passes readiness, so a stalled factory is exercised without
        stalling the chaos suite."""
        total = 0.0
        for p in self._matching_engine(None, attempt):
            if p.spawn_latency_s > 0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(p, "spawn_latency", "SPAWN", "autoscaler")
                total += p.spawn_latency_s
        return total

    # -- channel decision points (disaggregated KV handoff) ----------------

    def take_handoff_drop(self, request_id: int) -> bool:
        """Channel hook: should this KV transfer be dropped in flight?  A
        dropped transfer must surface as a fallback re-prefill on the
        decode pool — never a lost or duplicated stream."""
        for p in self._matching_engine(None, None):
            if p.handoff_drop_rate and self._roll(
                p, p.handoff_drop_rate, "handoff_drop",
                f"request-{request_id}", "channel",
            ):
                return True
        return False

    def take_handoff_latency(self) -> float:
        """Channel hook: simulated seconds added to this transfer.  Unlike
        :meth:`take_step_latency` it does NOT sleep — handoff latency is
        accounted into the transfer's deadline arithmetic, so chaos runs
        stay fast while still exercising the deadline path."""
        total = 0.0
        for p in self._matching_engine(None, None):
            if p.handoff_latency_s > 0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(p, "handoff_latency", "TRANSFER", "channel")
                total += p.handoff_latency_s
        return total

    def take_handoff_corrupt(self, request_id: int) -> bool:
        """Channel hook: should this transfer's payload arrive corrupted?
        The channel detects it via checksum mismatch and the router treats
        it exactly like a drop (fallback re-prefill) — corrupted KV bytes
        must never be injected into a decode replica."""
        for p in self._matching_engine(None, None):
            if p.handoff_corrupt_rate and self._roll(
                p, p.handoff_corrupt_rate, "handoff_corrupt",
                f"request-{request_id}", "channel",
            ):
                return True
        return False

    # -- link decision points (multi-channel failover) ---------------------

    def take_channel_down(self, channel: str) -> bool:
        """Link hook: should this interconnect link die NOW?  Consulted by
        the ChannelSet both at tick time and between a transfer's begin
        and complete — a mid-transfer death must fail the hop over to a
        sibling link, never lose or duplicate the stream."""
        for p in self._matching_channel(channel):
            if p.channel_down_rate and self._roll(
                p, p.channel_down_rate, "channel_down",
                f"channel-{channel}", "channel",
            ):
                return True
        return False

    def channel_bandwidth_factor(self, channel: str) -> float:
        """Link hook: the bandwidth multiplier for this link (1.0 = no
        brownout).  Accounted into the transfer's latency arithmetic like
        :meth:`take_handoff_latency` — never slept; the shared ``limit``
        budget caps how many transfers ride the degraded link."""
        factor = 1.0
        for p in self._matching_channel(channel):
            if 0.0 < p.channel_degrade < 1.0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(
                        p, "channel_degrade", "TRANSFER", f"channel-{channel}"
                    )
                factor *= p.channel_degrade
        return factor

    # -- socket decision points (models/transport.py wire seams) -----------

    def take_sock_truncate(self, peer: str) -> bool:
        """Transport send seam: should this frame be cut mid-body?  The
        sender writes a prefix of the frame and the connection dies — the
        receiver must surface a typed decode failure, never install a
        partial payload, and never hang waiting for the rest."""
        for p in self._matching_engine(None, None):
            if p.sock_truncate_rate and self._roll(
                p, p.sock_truncate_rate, "sock_truncate",
                f"peer-{peer}", "transport",
            ):
                return True
        return False

    def take_sock_reset(self, peer: str) -> bool:
        """Transport send seam: should the peer connection reset
        (ECONNRESET-shaped) before this frame lands?  Nothing of the frame
        arrives; the sender must attribute the failure to the in-flight
        rid and unwind its in-flight-bytes reservation."""
        for p in self._matching_engine(None, None):
            if p.sock_reset_rate and self._roll(
                p, p.sock_reset_rate, "sock_reset",
                f"peer-{peer}", "transport",
            ):
                return True
        return False

    def take_sock_latency(self) -> float:
        """Transport seam: simulated seconds this frame spends on the
        wire.  Accounted into the transfer deadline ladder like
        :meth:`take_handoff_latency` — never slept."""
        total = 0.0
        for p in self._matching_engine(None, None):
            if p.sock_latency_s > 0:
                with self._lock:
                    if not self._budget_ok(p):
                        continue
                    self._record(p, "sock_latency", "FRAME", "transport")
                total += p.sock_latency_s
        return total

    def take_sock_partition(self, peer: str, step: int | None = None) -> bool:
        """Transport send seam: should this frame silently vanish (one-way
        partition)?  Unlike reset/truncate the connection stays OPEN — the
        peer keeps talking to us, we just never land anything on it.  Only
        liveness (heartbeat expiry) or anti-entropy on reconnect may heal
        the divergence; the data path must never wedge on it."""
        for p in self._matching_peer(peer, step):
            if p.sock_partition_rate and self._roll(
                p, p.sock_partition_rate, "sock_partition",
                f"peer-{peer}", "transport",
            ):
                return True
        return False

    def take_peer_hang(self) -> bool:
        """Transport recv seam: should the receiver stall silently this
        poll (frames buffered but not processed, heartbeats unanswered)?
        Storm-budgeted like ``watch_hangs`` — liveness detection, not the
        data path, is what must catch it."""
        for p in self._matching_engine(None, None):
            with self._lock:
                if p.peer_hang > 0 and self._budget_ok(p):
                    p.peer_hang -= 1
                    self._record(p, "peer_hang", "POLL", "transport")
                    return True
        return False

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def total_injected(self) -> int:
        with self._lock:
            return sum(self._counts.values())

    # -- internals ---------------------------------------------------------

    def _matching(self, verb: str, kind: str) -> list[FaultProfile]:
        with self._lock:
            return [
                p
                for p in self._profiles
                if (not p.verbs or verb in p.verbs)
                and (not p.kinds or kind in p.kinds)
            ]

    def _matching_engine(self, slot: int | None, step: int | None) -> list[FaultProfile]:
        """Profiles matching an engine (slot, step) decision point — the
        data-plane twin of :meth:`_matching` (None matches everything,
        used by the slot-agnostic latency hook)."""
        with self._lock:
            return [
                p
                for p in self._profiles
                if (slot is None or not p.slots or slot in p.slots)
                and (step is None or not p.steps or step in p.steps)
            ]

    def _matching_channel(self, channel: str) -> list[FaultProfile]:
        """Profiles matching an interconnect link by name — the channel-set
        twin of :meth:`_matching_engine` (empty scope matches every link)."""
        with self._lock:
            return [
                p
                for p in self._profiles
                if not p.channels or channel in p.channels
            ]

    def _matching_replica(self, replica: int, tick: int) -> list[FaultProfile]:
        """Profiles matching a fleet (replica, tick) decision point — the
        router twin of :meth:`_matching_engine` (``steps`` doubles as the
        tick scope so one env spec drives both layers)."""
        with self._lock:
            return [
                p
                for p in self._profiles
                if (not p.replicas or replica in p.replicas)
                and (not p.steps or tick in p.steps)
            ]

    def _matching_peer(self, peer: str, step: int | None) -> list[FaultProfile]:
        """Profiles matching a transport peer by name — the partition twin
        of :meth:`_matching_channel` (empty scope matches every peer;
        ``steps`` doubles as the sender's per-conn frame counter so a
        partition window can be pinned to specific frames)."""
        with self._lock:
            return [
                p
                for p in self._profiles
                if (not p.peers or peer in p.peers)
                and (step is None or not p.steps or step in p.steps)
            ]

    def _matching_sched(self, scheduler: int) -> list[FaultProfile]:
        """Profiles matching a contention-harness scheduler by worker
        index — the scheduler twin of :meth:`_matching_engine` (empty
        scope matches every scheduler)."""
        with self._lock:
            return [
                p
                for p in self._profiles
                if not p.schedulers or scheduler in p.schedulers
            ]

    def _take_counted(self, kind: str, attr: str) -> bool:
        for p in self._matching("GET", kind):
            with self._lock:
                if getattr(p, attr) > 0 and self._budget_ok(p):
                    setattr(p, attr, getattr(p, attr) - 1)
                    self._record(p, attr, "GET", kind)
                    return True
        return False

    def _roll(self, p: FaultProfile, rate: float, fault: str, verb: str, kind: str) -> bool:
        with self._lock:
            if not self._budget_ok(p):
                return False
            if self._rng.random() >= rate:
                return False
            self._record(p, fault, verb, kind)
            return True

    def _budget_ok(self, p: FaultProfile) -> bool:
        # called with the lock held
        return p.limit <= 0 or p.injected < p.limit

    def _record(self, p: FaultProfile, fault: str, verb: str, kind: str) -> None:
        # called with the lock held
        p.injected += 1
        self._counts[fault] = self._counts.get(fault, 0) + 1
        _INJECTED.inc(profile=p.name, fault=fault)
        JOURNAL.record_lazy(
            "faults", f"inject.{fault}", correlation=p.name,
            attrs=lambda: dict(verb=verb, kind=kind),
        )

    # -- env arming --------------------------------------------------------

    @staticmethod
    def from_env(raw: str) -> "FaultInjector":
        """Parse ``DRA_FAULTS`` (``error_rate=0.3,latency_ms=5,seed=7``)
        into an armed injector.  Unknown keys fail loudly — a typo'd chaos
        run that silently injects nothing proves the wrong thing."""
        fields = {}
        seed = 0
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            key, _, value = part.partition("=")
            if key == "seed":
                seed = int(value)
            elif key == "latency_ms":
                fields["latency_s"] = float(value) / 1000.0
            elif key == "step_latency_ms":
                fields["step_latency_s"] = float(value) / 1000.0
            elif key == "handoff_latency_ms":
                fields["handoff_latency_s"] = float(value) / 1000.0
            elif key == "handoff_drop":
                fields["handoff_drop_rate"] = float(value)
            elif key == "handoff_corrupt":
                fields["handoff_corrupt_rate"] = float(value)
            elif key == "channel_down":
                fields["channel_down_rate"] = float(value)
            elif key == "channel_degrade":
                fields["channel_degrade"] = float(value)
            elif key == "spawn_fail":
                fields["spawn_fail_rate"] = float(value)
            elif key == "spawn_latency_ms":
                fields["spawn_latency_s"] = float(value) / 1000.0
            elif key == "sock_latency_ms":
                fields["sock_latency_s"] = float(value) / 1000.0
            elif key == "sock_truncate":
                fields["sock_truncate_rate"] = float(value)
            elif key == "sock_reset":
                fields["sock_reset_rate"] = float(value)
            elif key == "sock_partition":
                fields["sock_partition_rate"] = float(value)
            elif key == "sched_commit_latency_ms":
                fields["sched_commit_latency_s"] = float(value) / 1000.0
            elif key in ("error_rate", "conflict_rate", "drop_rate", "latency_s",
                         "watch_hang_s", "nan_logits_rate", "step_raise_rate",
                         "step_latency_s", "replica_crash_rate",
                         "replica_wedge_rate", "stats_stale_rate",
                         "handoff_drop_rate", "handoff_latency_s",
                         "handoff_corrupt_rate", "spawn_fail_rate",
                         "spawn_latency_s", "sock_truncate_rate",
                         "sock_reset_rate", "sock_latency_s",
                         "sock_partition_rate",
                         "channel_down_rate", "sched_conflict_rate",
                         "sched_commit_latency_s"):
                fields[key] = float(value)
            elif key in ("error_code", "watch_gone", "watch_error_frames",
                         "watch_hangs", "peer_hang", "limit"):
                fields[key] = int(value)
            elif key == "verbs":
                fields["verbs"] = tuple(value.split("+"))
            elif key == "kinds":
                fields["kinds"] = tuple(value.split("+"))
            elif key == "channels":
                fields["channels"] = tuple(value.split("+"))
            elif key == "peers":
                fields["peers"] = tuple(value.split("+"))
            elif key in ("slots", "steps", "replicas", "schedulers"):
                fields[key] = tuple(int(v) for v in value.split("+"))
            else:
                raise ValueError(f"{ENV_VAR}: unknown fault key {key!r}")
        injector = FaultInjector(seed=seed)
        injector.arm(FaultProfile(name="env", **fields))
        return injector
