"""Structured logging setup (klog/logsapi analog, pkg/flags/logging.go).

Supports text and JSON formats like the reference's ``--logging-format``
bridge (logging.go:33-48); JSON output makes the driver's logs ingestible by
the same pipelines the k8s components feed.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
import traceback


class JSONFormatter(logging.Formatter):
    """JSON lines with full exception fidelity: ``logger.exception(...)``
    must not lose its traceback in JSON mode (the whole point of the
    format is machine-ingestible post-mortems), so ``exc_info`` is
    serialized structured — type, message, and traceback frames — and
    ``stack_info=True`` call-site stacks ride along as ``stack``."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            etype, exc, tb = record.exc_info
            doc["exc"] = {
                "type": etype.__name__ if etype else "",
                "message": str(exc),
                "traceback": [
                    ln.rstrip("\n")
                    for ln in traceback.format_exception(etype, exc, tb)
                ],
            }
        elif record.exc_text:
            # A text-format handler on the same record caches the rendered
            # traceback here; keep it rather than drop the exception.
            doc["exc"] = {"type": "", "message": "", "traceback": record.exc_text.splitlines()}
        if record.stack_info:
            doc["stack"] = record.stack_info.splitlines()
        return json.dumps(doc)


def get_logger(name: str, level: str | None = None) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        if os.environ.get("LOG_FORMAT", "text") == "json":
            handler.setFormatter(JSONFormatter())
        else:
            handler.setFormatter(
                logging.Formatter("%(asctime)s %(levelname).1s %(name)s] %(message)s")
            )
        logger.addHandler(handler)
        logger.propagate = False
    logger.setLevel((level or os.environ.get("LOG_LEVEL", "INFO")).upper())
    return logger
