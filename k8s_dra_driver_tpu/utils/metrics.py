"""Minimal Prometheus-style metrics registry.

The reference exposes client-go/workqueue collectors via promhttp on the
controller only (cmd/nvidia-dra-controller/main.go:194-214) and has NO
custom metrics — SURVEY.md §5 calls out that the BASELINE
claim-to-running-p50 metric needs new instrumentation.  This module provides
it for both binaries: counters, gauges and histograms with labels, rendered
in the Prometheus text exposition format.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass, field

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


def _label_key(labels: dict[str, str] | None) -> tuple:
    return tuple(sorted((labels or {}).items()))


def format_value(v: float) -> str:
    """Render one sample value for the text exposition format.  repr() of
    a Python float is the SHORTEST string that parses back to exactly the
    same double (float(format_value(v)) == v — the precision round-trip
    the parse-back tests pin), and the non-finite spellings are the ones
    the Prometheus text format defines ("+Inf"/"-Inf"/"NaN", not Python's
    "inf"/"nan", which scrapers reject)."""
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    return repr(v)


def escape_label_value(value: str) -> str:
    """Prometheus text-exposition escaping for label values: backslash,
    double quote and newline must be escaped or the rendered line is
    unparseable (and a crafted value could inject whole bogus samples)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _label_str(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key) + "}"


@dataclass
class Counter:
    """Mutation and render are lock-protected: /metrics scrapes run on
    DiagnosticsServer threads concurrently with driver-thread updates."""

    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_label_str(key)} {format_value(v)}")
        return out


@dataclass
class Gauge:
    name: str
    help: str
    _values: dict[tuple, float] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = sorted(self._values.items())
        for key, v in items:
            out.append(f"{self.name}{_label_str(key)} {format_value(v)}")
        return out


@dataclass
class Histogram:
    name: str
    help: str
    buckets: tuple = _DEFAULT_BUCKETS
    _counts: dict[tuple, list[int]] = field(default_factory=dict)
    _sums: dict[tuple, float] = field(default_factory=dict)
    _totals: dict[tuple, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def __post_init__(self):
        # Normalize declared buckets once so observe/quantile/render agree:
        # sorted, deduplicated, and with non-finite bounds DROPPED — the
        # +Inf bucket is implicit in the exposition format (rendered from
        # _totals), so an explicit float("inf") bound would emit a second,
        # misspelled le="inf" line that scrapers reject.  Original bound
        # objects are kept (not coerced to float) so an int bound 1 still
        # renders le="1", not le="1.0".
        seen: set[float] = set()
        norm = []
        for bound in sorted(self.buckets, key=float):
            fb = float(bound)
            if not math.isfinite(fb) or fb in seen:
                continue
            seen.add(fb)
            norm.append(bound)
        self.buckets = tuple(norm)

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def clear(self) -> None:
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def quantile(self, q: float, **labels) -> float:
        """Approximate quantile from bucket counts (upper bound of the bucket
        that crosses the rank) — the claim-latency p50/p90 readout."""
        key = _label_key(labels)
        with self._lock:
            total = self._totals.get(key, 0)
            if total == 0:
                return 0.0
            rank = q * total
            counts = list(self._counts[key])
        for i, bound in enumerate(self.buckets):
            if counts[i] >= rank:
                return bound
        return float("inf")

    def render(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            keys = sorted(self._totals)
            counts = {k: list(self._counts[k]) for k in keys}
            sums = dict(self._sums)
            totals = dict(self._totals)
        for key in keys:
            for i, bound in enumerate(self.buckets):
                bucket_key = key + (("le", str(bound)),)
                out.append(f"{self.name}_bucket{_label_str(bucket_key)} {counts[key][i]}")
            inf_key = key + (("le", "+Inf"),)
            out.append(f"{self.name}_bucket{_label_str(inf_key)} {totals[key]}")
            out.append(f"{self.name}_sum{_label_str(key)} {format_value(sums[key])}")
            out.append(f"{self.name}_count{_label_str(key)} {totals[key]}")
        return out


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, lambda: Counter(name, help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str = "", buckets: tuple = _DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, lambda: Histogram(name, help, buckets))

    def _get_or_create(self, name, factory):
        with self._lock:
            if name not in self._metrics:
                self._metrics[name] = factory()
            return self._metrics[name]

    def reset(self) -> None:
        """Zero every registered metric's recorded values, KEEPING the
        metric objects: modules bind them at import time (e.g.
        models/serve.py's ``_M_TOKENS``), so dropping the dict would
        silently fork live metrics off the rendered ``/metrics`` output.
        Tests reset the global REGISTRY between cases (autouse fixture in
        tests/conftest.py) so asserts are absolute, not before/after
        deltas against whatever earlier tests left behind."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            m.clear()

    def render(self) -> str:
        with self._lock:
            lines = []
            for metric in self._metrics.values():
                lines.extend(metric.render())
            return "\n".join(lines) + "\n"


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    if text == "NaN":
        return math.nan
    return float(text)


def _parse_labels(raw: str) -> tuple:
    """Inverse of _label_str's body: scan comma-separated k="v" pairs,
    undoing escape_label_value's three escapes."""
    labels = []
    i, n = 0, len(raw)
    while i < n:
        while i < n and raw[i] in ", ":
            i += 1
        if i >= n:
            break
        eq = raw.index("=", i)
        key = raw[i:eq]
        if eq + 1 >= n or raw[eq + 1] != '"':
            raise ValueError(f"malformed label pair at offset {i}: {raw!r}")
        j = eq + 2
        buf = []
        while j < n and raw[j] != '"':
            if raw[j] == "\\" and j + 1 < n:
                nxt = raw[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, "\\" + nxt))
                j += 2
            else:
                buf.append(raw[j])
                j += 1
        if j >= n:
            raise ValueError(f"unterminated label value: {raw!r}")
        labels.append((key, "".join(buf)))
        i = j + 1
    return tuple(sorted(labels))


def parse_prom_text(text: str) -> dict[str, dict[tuple, float]]:
    """Parse the text exposition format back into
    ``{metric_name: {label_key: value}}`` — the inverse of
    ``Registry.render()``.  Exists so tests can pin the round-trip
    contract (``parse_prom_text(render())`` recovers every sample value
    exactly, including ``le="+Inf"`` buckets and float sums to the last
    ulp) instead of grepping rendered lines with brittle substrings."""
    out: dict[str, dict[tuple, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            raw, value_part = rest.rsplit("}", 1)
            labels = _parse_labels(raw)
        else:
            name, value_part = line.split(None, 1)
            labels = ()
        out.setdefault(name, {})[labels] = _parse_value(value_part.strip())
    return out


REGISTRY = Registry()
