"""Claim-lifecycle flight recorder.

SURVEY.md §5: the reference driver has essentially no node-side
observability, and BENCH_r05.json shows the cost — a 240s data-plane
timeout diagnosed as ``"hung device link?"`` because no component kept a
record of what it was doing when it stalled.  This module is the record:
a bounded, thread-safe journal of timestamped lifecycle events, each
carrying a **correlation id** (claim UID, device name, request id) so a
single stall can be traced controller → allocator → node driver →
serving from one artifact.

Every claim-path component records here (controller/main.py,
scheduler/allocator.py, kube/resourceslice_controller.py,
plugin/driver.py, plugin/topology_daemon.py, models/serve.py); the tail
is exported via ``/debug/journal`` on the diagnostics endpoint and
embedded in every watchdog diag bundle (utils/watchdog.py).

Overhead is one lock acquisition and one deque append per event — cheap
enough for the claim path, deliberately NOT placed on per-token device
loops (the serving engine journals admissions and completions, never
individual decode steps).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field

DEFAULT_CAPACITY = 2048


@dataclass(frozen=True)
class Event:
    ts: float  # time.time() at record()
    component: str  # "allocator", "driver", "serve", ...
    event: str  # "prepare.start", "allocate.fail", ...
    correlation: str = ""  # claim UID / device name / request id
    attrs: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(self.ts))
            + f".{int(self.ts % 1 * 1000):03d}Z",
            "component": self.component,
            "event": self.event,
            **({"correlation": self.correlation} if self.correlation else {}),
            **({"attrs": self.attrs} if self.attrs else {}),
        }


class Journal:
    """Bounded ring of lifecycle events; drop-oldest under pressure so a
    chatty component can never block or OOM the process it observes."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events: deque[Event] = deque(maxlen=capacity)
        self._dropped = 0
        self._recorded = 0
        self._enabled = True
        self._sample_every = 1
        self._sample_seq = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        """Turn the ring on/off.  While off, ``record``/``record_lazy`` are
        near-free: hot paths keep their call sites, operators keep the
        off switch."""
        self._enabled = bool(enabled)

    def set_sampling(self, every: int) -> None:
        """Keep 1 of every ``every`` events (1 = keep all).  Applies only to
        ``record_lazy`` hot-path sites; direct ``record`` calls (rare,
        failure-path) are always kept."""
        self._sample_every = max(1, int(every))

    def _sampled_out(self) -> bool:
        if self._sample_every == 1:
            return False
        with self._lock:
            self._sample_seq += 1
            return self._sample_seq % self._sample_every != 0

    def record(self, component: str, event: str, correlation: str = "", **attrs) -> None:
        if not self._enabled:
            return
        e = Event(
            ts=time.time(),
            component=component,
            event=event,
            correlation=str(correlation),
            attrs=attrs,
        )
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self._dropped += 1
            self._recorded += 1
            self._events.append(e)

    def record_lazy(self, component: str, event: str, correlation: str = "",
                    attrs=None) -> None:
        """Hot-path variant: ``attrs`` is a zero-arg callable returning the
        attrs dict, invoked ONLY when the event will actually be kept.  A
        disabled or sampled-out journal never formats the payload — no
        per-record dict/list/str allocation on the allocate/prepare path."""
        if not self._enabled or self._sampled_out():
            return
        self.record(component, event, correlation,
                    **(attrs() if attrs is not None else {}))

    def tail(self, limit: int = 200, correlation: str | None = None,
             component: str | None = None) -> list[dict]:
        """Newest-last slice of the ring, optionally filtered — the shape
        ``/debug/journal`` serves and diag bundles embed."""
        with self._lock:
            events = list(self._events)
        if correlation is not None:
            events = [e for e in events if e.correlation == str(correlation)]
        if component is not None:
            events = [e for e in events if e.component == component]
        return [e.to_json() for e in events[-limit:]]

    def export_since(self, cursor: int) -> tuple[int, list[dict]]:
        """Events recorded after ``cursor`` (a value previously returned by
        this method; start from 0), plus the new cursor — the exactly-once
        shipping primitive for telemetry federation.  Exported docs carry
        the RAW epoch timestamp (``ts_s``) alongside the formatted one so
        the fleet merger can order events from many processes without
        re-parsing strings.  Events evicted from the ring before export
        show up as a larger skip: bounded loss, never an error."""
        with self._lock:
            total = self._recorded
            events = list(self._events)
        start = total - len(events)  # seq of events[0]
        skip = max(0, int(cursor) - start)
        return total, [
            {**e.to_json(), "ts_s": e.ts} for e in events[skip:]
        ]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self._events.maxlen,
                "buffered": len(self._events),
                "recorded": self._recorded,
                "dropped": self._dropped,
            }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0
            self._recorded = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


JOURNAL = Journal()
