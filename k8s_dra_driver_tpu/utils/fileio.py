"""Atomic file-write helper shared by checkpoint and CDI spec writers."""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path
from typing import Any


def write_json_atomic(path: Path, doc: Any, indent: int = 2) -> Path:
    """Write JSON via tmp-file + rename so readers never see a torn file."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=indent, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        os.unlink(tmp)
        raise
    return path
