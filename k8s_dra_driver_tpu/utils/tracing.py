"""Lightweight span tracing for the claim hot path.

SURVEY.md §5: the reference has no tracing spans (pprof only, controller
only).  This is a minimal structured tracer: nested spans with wall-time,
kept in a bounded ring buffer, exported via /debug/traces on the
diagnostics endpoint.  Zero dependencies; overhead is two clock reads per
span.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float
    duration_ms: float = 0.0
    attributes: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "start": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.start)),
            "durationMs": round(self.duration_ms, 3),
            **({"attributes": self.attributes} if self.attributes else {}),
            **(
                {"children": [c.to_json() for c in self.children]}
                if self.children
                else {}
            ),
        }


class Tracer:
    """Per-process tracer; completed root spans land in a ring buffer."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._finished: deque[Span] = deque(maxlen=capacity)
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **attributes):
        s = Span(name=name, start=time.time(), attributes=dict(attributes))
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        stack.append(s)
        t0 = time.perf_counter()
        try:
            yield s
        finally:
            s.duration_ms = (time.perf_counter() - t0) * 1000
            stack.pop()
            if parent is not None:
                parent.children.append(s)
            else:
                with self._lock:
                    self._finished.append(s)

    def add(self, span: Span) -> None:
        """Record an externally-constructed root span.  For retroactive
        timelines (e.g. a request's lifecycle assembled at retirement from
        burst-boundary timestamps) where a ``with span():`` block around
        the whole interval would force extra clock reads on the hot path."""
        with self._lock:
            self._finished.append(span)

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            spans = list(self._finished)[-limit:]
        return [s.to_json() for s in reversed(spans)]


TRACER = Tracer()


@dataclass(frozen=True)
class SpanRecord:
    """One federable span: flat (no object children), identified by
    ``span_id`` and stitched into a tree via ``parent_id`` AFTER transport.

    ``Span`` above is the in-process presentation shape; SpanRecord is the
    wire shape.  Timestamps ``t0``/``t1`` are in the RECORDING process's
    ``time.monotonic()`` domain — meaningless across processes until the
    fleet merger subtracts that process's clock offset (estimated from
    PING/PONG rtt) — which is exactly why they are shipped raw: the
    control plane owns the skew model, not the worker."""

    trace_id: str  # correlates every hop of one request, e.g. "req-17"
    span_id: str
    name: str  # "serve.request", "hop.prefill", "hop.wire", ...
    t0: float  # time.monotonic() at span start (recorder's clock)
    t1: float  # time.monotonic() at span end
    parent_id: str = ""
    attrs: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "name": self.name,
            "t0": self.t0,
            "t1": self.t1,
            **({"parent_id": self.parent_id} if self.parent_id else {}),
            **({"attrs": self.attrs} if self.attrs else {}),
        }

    @staticmethod
    def from_json(doc: dict) -> "SpanRecord":
        return SpanRecord(
            trace_id=str(doc.get("trace_id", "")),
            span_id=str(doc.get("span_id", "")),
            name=str(doc.get("name", "")),
            t0=float(doc.get("t0", 0.0)),
            t1=float(doc.get("t1", 0.0)),
            parent_id=str(doc.get("parent_id", "")),
            attrs=dict(doc.get("attrs", {}) or {}),
        )


class TraceBuffer:
    """Bounded ring of SpanRecords with a monotonic sequence cursor, so a
    shipper can export exactly-once without copying the whole ring each
    cadence: ``export_since(cursor)`` returns only records appended after
    the cursor, plus the new cursor.  Records evicted before export are
    simply gone (drop-oldest — telemetry must never block serving)."""

    def __init__(self, capacity: int = 1024):
        self._lock = threading.Lock()
        self._records: deque[SpanRecord] = deque(maxlen=capacity)
        self._seq = 0  # total records ever appended

    def mint_id(self, name: str) -> str:
        """Span ids unique across processes: pid-qualified sequence."""
        with self._lock:
            n = self._seq
        return f"s{os.getpid():x}.{name}.{n}"

    def record(self, trace_id: str, name: str, t0: float, t1: float, *,
               parent_id: str = "", span_id: str = "", **attrs) -> SpanRecord:
        rec = SpanRecord(
            trace_id=str(trace_id),
            span_id=span_id or self.mint_id(name),
            name=name,
            t0=float(t0),
            t1=float(t1),
            parent_id=str(parent_id),
            attrs=attrs,
        )
        with self._lock:
            self._records.append(rec)
            self._seq += 1
        return rec

    def add(self, rec: SpanRecord) -> None:
        with self._lock:
            self._records.append(rec)
            self._seq += 1

    def export_since(self, cursor: int) -> tuple[int, list[dict]]:
        """New records appended after ``cursor`` (a value previously
        returned by this method; start from 0).  Ring eviction shows up as
        a silently larger skip — bounded loss, never an error."""
        with self._lock:
            total = self._seq
            records = list(self._records)
        start = total - len(records)  # seq of records[0]
        skip = max(0, int(cursor) - start)
        return total, [r.to_json() for r in records[skip:]]

    def snapshot(self, limit: int = 256) -> list[dict]:
        with self._lock:
            records = list(self._records)[-limit:]
        return [r.to_json() for r in records]

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self._records.maxlen,
                "buffered": len(self._records),
                "recorded": self._seq,
            }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._seq = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


TRACES = TraceBuffer()
