"""Lightweight span tracing for the claim hot path.

SURVEY.md §5: the reference has no tracing spans (pprof only, controller
only).  This is a minimal structured tracer: nested spans with wall-time,
kept in a bounded ring buffer, exported via /debug/traces on the
diagnostics endpoint.  Zero dependencies; overhead is two clock reads per
span.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float
    duration_ms: float = 0.0
    attributes: dict = field(default_factory=dict)
    children: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "start": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(self.start)),
            "durationMs": round(self.duration_ms, 3),
            **({"attributes": self.attributes} if self.attributes else {}),
            **(
                {"children": [c.to_json() for c in self.children]}
                if self.children
                else {}
            ),
        }


class Tracer:
    """Per-process tracer; completed root spans land in a ring buffer."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._finished: deque[Span] = deque(maxlen=capacity)
        self._local = threading.local()

    @contextmanager
    def span(self, name: str, **attributes):
        s = Span(name=name, start=time.time(), attributes=dict(attributes))
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        parent = stack[-1] if stack else None
        stack.append(s)
        t0 = time.perf_counter()
        try:
            yield s
        finally:
            s.duration_ms = (time.perf_counter() - t0) * 1000
            stack.pop()
            if parent is not None:
                parent.children.append(s)
            else:
                with self._lock:
                    self._finished.append(s)

    def add(self, span: Span) -> None:
        """Record an externally-constructed root span.  For retroactive
        timelines (e.g. a request's lifecycle assembled at retirement from
        burst-boundary timestamps) where a ``with span():`` block around
        the whole interval would force extra clock reads on the hot path."""
        with self._lock:
            self._finished.append(span)

    def recent(self, limit: int = 50) -> list[dict]:
        with self._lock:
            spans = list(self._finished)[-limit:]
        return [s.to_json() for s in reversed(spans)]


TRACER = Tracer()
