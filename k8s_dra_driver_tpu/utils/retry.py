"""Unified retry / backoff / circuit-breaker policy for API-server traffic.

The reference driver inherits all of its control-plane resilience from
client-go (reflector relists, workqueue rate limiters, flowcontrol token
buckets); our stdlib transport re-provisioned the happy path but left every
caller to improvise its own failure handling — one-shot ``_request``, fixed
1s watch reconnect sleeps, ad-hoc ``TransientError`` parking in the slice
manager.  This module is the single policy layer they all share:

* :class:`RetryPolicy` — jittered exponential backoff parameters plus the
  retryable-error classification (:func:`is_retryable`: 429/5xx and
  transport errors retry, other 4xx never do — a Conflict must be healed by
  re-get, not replay).
* :class:`Backoff` — the schedule iterator (``next_delay``/``reset``/
  ``sleep``); *every* reconnect/poll loop in the tree uses it, enforced by
  the ``sleep-retry`` lint check (tools/lint.py).
* :class:`RetryBudget` — gRPC-throttling-style token bucket shared across
  calls so a broad outage cannot amplify into a retry storm.
* :class:`CircuitBreaker` — per-endpoint-class: opens after N consecutive
  retryable failures, fails fast while open, half-open probe after a
  cooldown.  State is observable as ``dra_circuit_state`` (0 closed /
  1 half-open / 2 open) and journal ``breaker.*`` events.
* :func:`call_with_retry` — the one retry loop, wired to the metrics
  (``dra_api_retries_total``) and the journal.

Thread-safe; clocks and sleeps are injectable so tests run in microseconds.
"""

from __future__ import annotations

import http.client
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

_RETRIES = REGISTRY.counter(
    "dra_api_retries_total",
    "Retried API operations, by op and failure reason",
)
_CIRCUIT_STATE = REGISTRY.gauge(
    "dra_circuit_state",
    "Circuit breaker state per endpoint class (0 closed, 1 half-open, 2 open)",
)
_CIRCUIT_TRANSITIONS = REGISTRY.counter(
    "dra_circuit_transitions_total",
    "Circuit breaker state transitions, by endpoint class and target state",
)


class CircuitOpenError(OSError):
    """Fail-fast rejection while a breaker is open.

    An ``OSError`` with ``code=503`` so every layer that already classifies
    transport errors as transient (``is_retryable``, the slice controller's
    ``(APIError, OSError)`` guards) treats it as retryable-later without
    new special cases."""

    def __init__(self, message: str):
        super().__init__(message)
        self.code = 503


def is_retryable(exc: BaseException) -> bool:
    """The classification: 429 and 5xx retry, other HTTP codes don't,
    transport-level failures (connection refused/reset/timeout, truncated
    responses) always retry.  Duck-typed on ``.code`` so it covers both
    ``fakeserver.APIError`` and ``urllib.error.HTTPError``."""
    code = getattr(exc, "code", None)
    if isinstance(code, int):
        return code == 429 or code >= 500
    return isinstance(exc, (OSError, http.client.HTTPException))


@dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff parameters + error classification.

    ``jitter`` is the fraction of each delay that is randomized downward
    (full jitter over ``[delay*(1-jitter), delay]``), de-synchronizing
    reconnect herds after an API-server blip."""

    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    retry_on: Callable[[BaseException], bool] = is_retryable


DEFAULT_POLICY = RetryPolicy()
# Watch reconnects have no attempt cap (the loop runs for the process
# lifetime); only the schedule matters.
DEFAULT_WATCH_POLICY = RetryPolicy(
    max_attempts=0, base_delay_s=0.2, max_delay_s=30.0
)


class Backoff:
    """The schedule iterator for one retry/reconnect loop.

    ``reset()`` on success is the contract: a loop that never resets turns
    one transient blip into permanent slow reconnects."""

    def __init__(
        self,
        policy: RetryPolicy = DEFAULT_POLICY,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._policy = policy
        self._rng = rng or random
        self._sleep = sleep
        self._attempt = 0

    @property
    def attempts(self) -> int:
        return self._attempt

    def next_delay(self) -> float:
        p = self._policy
        delay = min(p.max_delay_s, p.base_delay_s * (p.multiplier ** self._attempt))
        self._attempt += 1
        if p.jitter:
            delay *= 1.0 - p.jitter * self._rng.random()
        return delay

    def reset(self) -> None:
        self._attempt = 0

    def sleep(self) -> None:
        self._sleep(self.next_delay())


class ContentionBackoff:
    """Contention-adaptive backoff shaping for optimistic-concurrency loops.

    A 409 Conflict is deliberately NOT retryable-in-place (``is_retryable``):
    the caller must re-get and replan.  This class shapes how long it waits
    *before* that replan.  Two signals drive the delay:

    * **observed 409 density** — the conflict fraction over a sliding
      window of recent attempts.  When N schedulers race one store, high
      density means the replan will likely collide again, so everyone
      should spread out; near-zero density means conflicts are isolated
      blips and waiting is pure latency.
    * **consecutive-conflict streak** — classic exponential growth, but
      ``on_success()`` resets the streak (the reset-on-success contract
      ``Backoff`` documents) so one bad burst never becomes a permanently
      slow scheduler.  The never-reset variant is exactly the naive
      baseline the contention bench A/B quantifies: early losers inherit
      compounding delays and starve.

    Delay = ``base * 2^streak``, scaled by density (a near-idle store pays
    ~0), capped at ``max_delay_s``, with full downward jitter — jitter is
    what desynchronizes schedulers that conflicted at the same instant.
    rng and sleep are injectable for deterministic tests, same as
    ``Backoff``."""

    def __init__(
        self,
        base_delay_s: float = 0.001,
        max_delay_s: float = 0.1,
        window: int = 32,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self._base = base_delay_s
        self._max = max_delay_s
        self._window = window
        self._rng = rng or random
        self._sleep = sleep
        self._outcomes: deque = deque(maxlen=window)  # True per conflict
        self._streak = 0

    @property
    def streak(self) -> int:
        return self._streak

    @property
    def density(self) -> float:
        """Conflict fraction over the sliding window (0.0 when no attempt
        has been observed yet — an idle loop has no evidence of contention)."""
        if not self._outcomes:
            return 0.0
        return sum(self._outcomes) / len(self._outcomes)

    def on_conflict(self) -> None:
        self._outcomes.append(True)
        self._streak += 1

    def on_success(self) -> None:
        """Reset the streak; the density window keeps its history so a
        single success amid a storm doesn't zero the shaping signal."""
        self._outcomes.append(False)
        self._streak = 0

    def next_delay(self) -> float:
        if self._streak == 0:
            return 0.0
        grown = self._base * (2.0 ** min(self._streak - 1, 16))
        # Density scaling: a lone conflict on a quiet store waits ~base;
        # the same streak under a dense 409 storm waits the full grown
        # delay.  The +base floor keeps a conflicted loop from busy-spinning.
        delay = min(self._max, self._base + grown * self.density)
        return delay * (1.0 - 0.5 * self._rng.random())

    def sleep(self) -> None:
        d = self.next_delay()
        if d > 0:
            self._sleep(d)


class RetryBudget:
    """Process-wide retry throttle (the gRPC retry-throttling shape):
    every retry spends a token, every success refills ``refill_per_success``
    up to ``cap``.  Under a broad outage the budget drains and callers fail
    fast instead of multiplying load on a struggling API server."""

    def __init__(self, cap: float = 32.0, refill_per_success: float = 0.5):
        self._cap = cap
        self._refill = refill_per_success
        self._tokens = cap
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self._cap, self._tokens + self._refill)

    def remaining(self) -> float:
        with self._lock:
            return self._tokens


class CircuitBreaker:
    """Per-endpoint-class breaker: ``closed`` → (N consecutive retryable
    failures) → ``open`` (fail fast) → (cooldown) → ``half_open`` (one
    probe) → ``closed`` on success, back to ``open`` on failure.

    Only *retryable-class* failures trip it: a 404/409 means the server is
    healthy and the caller is wrong."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"
    _GAUGE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

    def __init__(
        self,
        endpoint: str = "",
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.endpoint = endpoint
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False
        _CIRCUIT_STATE.set(0, endpoint=endpoint)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request proceed right now?  While half-open exactly one
        in-flight probe is admitted; its outcome decides the next state."""
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if self._clock() - self._opened_at < self.reset_timeout_s:
                    return False
                self._transition(self.HALF_OPEN)
                self._probing = True
                return True
            # half-open: admit one probe at a time
            if not self._probing:
                self._probing = True
                return True
            return False

    def on_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != self.CLOSED:
                self._transition(self.CLOSED)

    def on_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == self.HALF_OPEN or (
                self._state == self.CLOSED
                and self._failures >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def cooldown_remaining(self) -> float:
        """Seconds until an OPEN breaker will admit its half-open probe
        (0.0 when closed or already probe-eligible).  The transport's
        reconnect scheduler reads this instead of poking ``allow()`` —
        ``allow()`` is a state transition (it STARTS the probe), while a
        status page or a pacing decision only wants to look."""
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at)
            )

    def trip(self) -> None:
        """Force the breaker open immediately, bypassing the consecutive-
        failure count.  For callers with DIRECT evidence the endpoint is
        dead (the fleet router catching a replica crash) — counting to
        ``failure_threshold`` would just route more traffic into the
        corpse first."""
        with self._lock:
            self._failures = max(self._failures, self.failure_threshold)
            self._probing = False
            if self._state != self.OPEN:
                self._opened_at = self._clock()
                self._transition(self.OPEN)

    def _transition(self, to: str) -> None:
        # called with the lock held
        self._state = to
        _CIRCUIT_STATE.set(self._GAUGE_VALUE[to], endpoint=self.endpoint)
        _CIRCUIT_TRANSITIONS.inc(endpoint=self.endpoint, to=to)
        JOURNAL.record(
            "retry", f"breaker.{to}", correlation=self.endpoint,
            failures=self._failures,
        )


def _reason(exc: BaseException) -> str:
    code = getattr(exc, "code", None)
    return str(code) if isinstance(code, int) else type(exc).__name__


def call_with_retry(
    fn: Callable[[], object],
    *,
    policy: RetryPolicy = DEFAULT_POLICY,
    breaker: Optional[CircuitBreaker] = None,
    budget: Optional[RetryBudget] = None,
    op: str = "",
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
):
    """Run ``fn`` under the policy.  Raises the last error when attempts,
    budget or classification say stop; raises :class:`CircuitOpenError`
    without calling ``fn`` while the breaker is open."""
    backoff = Backoff(policy, rng=rng, sleep=sleep)
    attempt = 1
    while True:
        if breaker is not None and not breaker.allow():
            raise CircuitOpenError(
                f"circuit open for {breaker.endpoint or op or 'endpoint'}"
            )
        try:
            result = fn()
        except Exception as exc:
            retryable = policy.retry_on(exc)
            if breaker is not None and retryable:
                breaker.on_failure()
            if (
                not retryable
                or attempt >= policy.max_attempts
                or (budget is not None and not budget.take())
            ):
                raise
            _RETRIES.inc(op=op, reason=_reason(exc))
            JOURNAL.record(
                "retry", "call.retry", correlation=op,
                attempt=attempt, error=f"{type(exc).__name__}: {exc}",
            )
            backoff.sleep()
            attempt += 1
        else:
            if breaker is not None:
                breaker.on_success()
            if budget is not None:
                budget.on_success()
            return result
