# Build/test entry points (reference Makefile:57-117 analog: cmds/test/lint/
# coverage targets, adapted to a Python+C++ tree).

PYTHON ?= python
CPP_DIR := k8s_dra_driver_tpu/tpuinfo/cpp

.PHONY: all native test asan-test bench bench-prefix chaos chaos-serve chaos-fleet chaos-disagg chaos-autoscale chaos-transport chaos-rebalance sim-cluster sim-contention demo dryrun lint analyze perf-smoke helm-template clean

all: native

# Native components: libtpuinfo.so + tpu-ctl (the cgo/nvidia-smi boundary).
native:
	$(MAKE) -C $(CPP_DIR)

# Full unit/integration suite (the reference's `go test -race -cover` slot).
test: native
	$(PYTHON) -m pytest tests/ -q

# Native shim + daemon under ASAN/UBSAN (SURVEY.md §5: we add sanitizers
# the reference's all-Go tree never needed).  The sanitized daemon serves
# one full protocol round trip so leaks/UB in the hot path surface here.
asan-test:
	$(MAKE) -C $(CPP_DIR) libtpuinfo_asan.so tpu_topology_daemon_asan
	$(PYTHON) tools/asan_daemon_check.py

# Headline benchmark (claim-to-running p50 + live data-plane proof).
bench:
	$(PYTHON) bench.py

# Fleet prefix-cache macrobench (<4min, CPU, seeded): shared-prefix trace
# replayed through a 4-replica sim fleet, per-engine caches vs the
# FleetPrefixIndex (depth-aware routing + modeled cross-replica pulls) —
# one JSON line with the TTFT/attainment A/B and hit provenance.
bench-prefix:
	JAX_PLATFORMS=cpu $(PYTHON) bench.py prefix_fleet

# Chaos suite (<10s): the allocator→prepare→unprepare loop under injected
# API faults (utils/faults.py) — error storms, conflict storms, dropped
# connections, watch outages — proving the retry/breaker layer converges
# with zero lost claims.
chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_chaos.py -q

# Serving chaos suite (<10s, CPU, seeded): deadlines, load shedding,
# poisoned-request quarantine with bit-equal survivor replay, and
# drain/snapshot/restore — the SLO layer under injected engine faults.
chaos-serve:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_serve_chaos.py -q

# Fleet chaos suite (<15s, CPU, seeded): replica crash/wedge/stale-stats
# faults against a 3-replica FleetRouter — health-gated routing,
# live-migration evacuation with bit-equal stream continuation, and
# fleet-level admission/shedding.
chaos-fleet:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_fleet_chaos.py -q

# Disaggregation chaos suite (<15s, CPU, seeded): KV-handoff transfers
# dropped/corrupted/past-deadline mid-flight between the prefill and
# decode pools — zero lost or duplicated streams, bit-equal re-prefill
# fallback, balanced per-pool block accounting.
chaos-disagg:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_disagg_chaos.py -q

# Autoscaler chaos suite (<15s, CPU, seeded): a flash-crowd trace drives
# the closed loop while spawn_fail/spawn_latency_ms/replica_crash faults
# break its actuators — zero lost or duplicated streams, completions
# bit-equal to an unfaulted reference, one journal correlation per
# scaling action, balanced block accounting at idle.
chaos-autoscale:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_autoscale_chaos.py -q

# KV transport chaos suite (CPU, seeded): framed transfers between the
# prefill and decode pools over REAL byte pipes under sock_truncate/
# sock_reset/sock_latency_ms/peer_hang faults, plus one genuine
# two-process run that SIGKILLs the decode worker mid-transfer — zero
# lost or duplicated streams, bit-equal recovery, breaker-gated
# reconnect, in-flight bytes drained to zero.
chaos-transport:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_transport_chaos.py -q

# Rebalance chaos suite (<20s, CPU, seeded): multi-link channel sets
# under channel_down/channel_degrade faults (mid-transfer failover to a
# sibling link, bit-equal, zero re-prefill), KV-demand admission
# backpressure (starved handoffs park then complete; impossible streams
# fire the deadlock detector and collapse unified), and scale_move pool
# rebalancing under replica crashes — zero lost or duplicated streams,
# balanced block accounting.
chaos-rebalance:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_rebalance_chaos.py -q

# Cluster-scale gang allocator suite (<30s, CPU, seeded; tier-1 via
# tests/): synthetic-cluster churn with watch storms driving the REAL
# AllocationIndex + plan()/plan_gang() — every claim accounted exactly
# once (relist audits, zero leaks at drain), gang atomicity under 409/500
# storms, deterministic reports, and a 10k-pool build with flat plan()
# latency.
sim-cluster:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_cluster_sim.py tests/test_gang_alloc.py -q

# Multi-scheduler contention suite (<60s, CPU, seeded; includes the
# slow-marked 10k-pool acceptance run tier-1 skips): N scheduler threads
# race plan()/allocate_gang() against one store with real CAS + admission
# semantics — exactly-once commits under 409 storms and concurrent gang
# unwinds, the naive-vs-conflict-aware fairness/waste A/B, and the
# starvation detector firing (diag bundle + journal) for a blackout
# victim while staying silent on the fixed path.
sim-contention:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/test_contention.py -q

# Closed-loop quickstart walkthrough.
demo:
	$(PYTHON) -m k8s_dra_driver_tpu.e2e.demo

# Single-chip compile check + 8-device sharded dry run.
dryrun:
	XLA_FLAGS=--xla_force_host_platform_device_count=8 $(PYTHON) __graft_entry__.py

# Static analysis (the reference's golangci-lint slot, .golangci.yaml:2-12):
# syntax via compileall + the first-party AST linter (tools/lint.py) + the
# helm chart consistency check + a full hermetic chart render
# (tools/helm_render.py — the `helm template` substitute; no helm binary).
lint:
	$(PYTHON) -m compileall -q k8s_dra_driver_tpu tests tools bench.py __graft_entry__.py
	$(PYTHON) tools/lint.py k8s_dra_driver_tpu tests bench.py __graft_entry__.py tools
	$(PYTHON) tools/helm_check.py
	$(PYTHON) -m tools.helm_render deployments/helm/tpu-dra-driver >/dev/null

# Whole-program invariant analyzer (tools/analysis): lock-discipline,
# jit-purity, terminal-funnel, block-accounting over a shared module index.
# Exits non-zero on NEW findings; tools/analysis/baseline.json suppresses
# (visibly) inherited ones.  Also enforced in tier-1 via tests/test_lint.py.
analyze:
	$(PYTHON) tools/lint.py --analyze k8s_dra_driver_tpu tools

# Hot-path perf budget guard (<30s; also runs inside `make test` via
# tests/test_perf_smoke.py): fails if allocation stops being
# O(changed pools) or prepare batches stop group-committing.
perf-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) tools/perf_smoke.py

# Render the chart to stdout (helm template substitute).
helm-template:
	$(PYTHON) -m tools.helm_render deployments/helm/tpu-dra-driver

# Container images: host-arch, UBI variant, and the multi-arch manifest
# (deployments/container/multi-arch.mk; reference multi-arch.mk analog).
image image-ubi image-all image-push:
	$(MAKE) -f deployments/container/multi-arch.mk $@

clean:
	$(MAKE) -C $(CPP_DIR) clean
	rm -rf .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
