"""Cross-check tools/helm_render.py against REAL ``helm template``.

The first-party renderer implements the Go-template subset the chart uses;
this script pins that subset's SEMANTICS to upstream helm wherever a helm
binary exists (CI has one; the hermetic dev environment does not — there the
golden tests in tests/test_helm_render.py hold the line instead).

For each values configuration it renders the chart both ways, parses the
document streams, normalizes (sort by kind/name — document ORDER is a
filename artifact in both renderers), and deep-compares the object trees.
Whitespace and comments are out of scope by construction: the comparison is
post-YAML-parse.

Exit codes: 0 = all configs match, 1 = divergence (diff printed),
3 = no helm binary on PATH (skipped).
"""

from __future__ import annotations

import pathlib
import shutil
import subprocess
import sys

import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
CHART = REPO / "deployments" / "helm" / "tpu-dra-driver"

# The same configurations the goldens pin (tests/test_helm_render.py).
CONFIGS: dict[str, list[str]] = {
    "default": [],
    "openshift-extender": [
        "openshift.enabled=true",
        "extenderPort=8082",
        "extenderTLSSecret=extender-tls",
        'extenderAllowedCIDRs=["10.0.0.0/28"]',
    ],
    "fake-minimal": [
        'deviceClasses=["tpu"]',
        "fakeTopology=v5e-16",
        "httpPort=-1",
        "image.tag=dev",
    ],
}


def _key(doc: dict) -> tuple:
    return (
        doc.get("kind", ""),
        doc.get("metadata", {}).get("name", ""),
        doc.get("metadata", {}).get("namespace", ""),
    )


def _ours(sets: list[str]) -> dict[tuple, dict]:
    sys.path.insert(0, str(REPO))
    from tools.helm_render import _parse_set, render_chart_docs

    docs = render_chart_docs(CHART, values_override=_parse_set(sets))
    return {_key(d): d for d in docs}


def _helms(sets: list[str]) -> dict[tuple, dict]:
    cmd = ["helm", "template", "tpu-dra-driver", str(CHART),
           "--namespace", "tpu-dra-driver"]
    for pair in sets:
        # helm's --set grammar has no JSON lists/objects ({a,b} only);
        # --set-json carries them with the same semantics _parse_set's
        # yaml.safe_load gives the first-party renderer.
        raw = pair.partition("=")[2]
        if raw.startswith("[") or raw.startswith("{"):
            cmd += ["--set-json", pair]
        else:
            cmd += ["--set", pair]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"helm template failed (rc={proc.returncode}): {proc.stderr.strip()}"
        )
    docs = [d for d in yaml.safe_load_all(proc.stdout) if d is not None]
    return {_key(d): d for d in docs}


def main() -> int:
    if shutil.which("helm") is None:
        print("helm_crosscheck: no helm binary on PATH — skipped")
        return 3
    failed = False
    for name, sets in CONFIGS.items():
        ours, helms = _ours(sets), _helms(sets)
        if ours == helms:
            print(f"helm_crosscheck: {name}: {len(ours)} docs match")
            continue
        failed = True
        print(f"helm_crosscheck: {name}: DIVERGED", file=sys.stderr)
        for k in sorted(set(ours) | set(helms), key=str):
            a, b = ours.get(k), helms.get(k)
            if a != b:
                print(f"--- {k}: ours={'<absent>' if a is None else ''}"
                      f" helm={'<absent>' if b is None else ''}",
                      file=sys.stderr)
                if a is not None and b is not None:
                    print(yaml.safe_dump({"ours": a, "helm": b}),
                          file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
