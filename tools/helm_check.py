#!/usr/bin/env python
"""Helm chart consistency check — the render-test substitute.

No helm binary exists in this image, so template output cannot be rendered
in CI; this checker statically pins the contract that most often breaks:

  * every ``.Values.x.y`` referenced by a template exists in values.yaml;
  * every ``include "name"`` resolves to a ``define`` in the chart;
  * every value defined in values.yaml is referenced somewhere (dead
    values are usually a renamed-but-not-updated template).

Usage: python tools/helm_check.py [chart_dir]   (exit 1 on findings)
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

import yaml

DEFAULT_CHART = Path(__file__).parent.parent / "deployments" / "helm" / "tpu-dra-driver"

VALUES_RE = re.compile(r"\.Values\.([A-Za-z0-9_.]+)")
INCLUDE_RE = re.compile(r'include\s+"([^"]+)"')
DEFINE_RE = re.compile(r'define\s+"([^"]+)"')


def value_paths(doc, prefix=()) -> set[tuple[str, ...]]:
    """All key paths in the values document (internal nodes included)."""
    out: set[tuple[str, ...]] = set()
    if isinstance(doc, dict):
        for key, val in doc.items():
            path = prefix + (str(key),)
            out.add(path)
            out |= value_paths(val, path)
    return out


def check_chart(chart: Path) -> list[str]:
    values_file = chart / "values.yaml"
    values = yaml.safe_load(values_file.read_text()) or {}
    defined = value_paths(values)

    findings: list[str] = []
    referenced: set[tuple[str, ...]] = set()
    defines: set[str] = set()
    includes: list[tuple[Path, int, str]] = []

    templates = sorted(p for p in (chart / "templates").rglob("*") if p.is_file())
    for tpl in templates:
        raw = tpl.read_text()
        for name in DEFINE_RE.findall(raw):
            defines.add(name)
        # Pragmas are read from the RAW text (they live in comments), then
        # {{/* ... */}} blocks are blanked so documentation mentions of
        # .Values.* neither fail the check nor mask dead values.
        pragma_lines = {
            i for i, line in enumerate(raw.splitlines(), 1) if "helm-check: allow" in line
        }
        text = re.sub(
            r"\{\{-?\s*/\*.*?\*/\s*-?\}\}",
            lambda m: re.sub(r"[^\n]", " ", m.group(0)),
            raw,
            flags=re.DOTALL,
        )
        lines = text.splitlines()
        for lineno, line in enumerate(lines, 1):
            # A `helm-check: allow` pragma within the 4 preceding lines (or
            # inline) skips the defined-in-values requirement — for guards
            # that must reference a value users are FORBIDDEN to set, like
            # .Values.namespace.
            allowed = any(
                i in pragma_lines for i in range(max(1, lineno - 4), lineno + 1)
            )
            for ref in VALUES_RE.findall(line):
                path = tuple(ref.split("."))
                referenced.add(path)
                if path not in defined and not allowed:
                    findings.append(
                        f"{tpl.name}:{lineno}: .Values.{ref} is not defined in values.yaml"
                    )
            for name in INCLUDE_RE.findall(line):
                includes.append((tpl, lineno, name))

    for tpl, lineno, name in includes:
        if name not in defines:
            findings.append(f'{tpl.name}:{lineno}: include "{name}" has no define')

    # dead values: no leaf nor ancestor referenced anywhere
    for path in sorted(defined):
        # internal nodes are fine if any descendant is referenced
        if any(r[: len(path)] == path for r in referenced):
            continue
        if any(path[: len(r)] == r for r in referenced):
            continue  # whole-subtree reference (`with .Values.x` style)
        findings.append(
            f"values.yaml: {'.'.join(path)} is never referenced by any template"
        )
    return findings


def main(argv: list[str]) -> int:
    chart = Path(argv[1]) if len(argv) > 1 else DEFAULT_CHART
    findings = check_chart(chart)
    for f in findings:
        print(f)
    print(f"helm-check: {chart.name}: {len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
