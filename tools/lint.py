#!/usr/bin/env python
"""First-party static analysis — the golangci-lint slot in CI.

The reference runs nine linters on every PR (.golangci.yaml:2-12,
.github/workflows/golang.yaml:27-49); this image bakes no Python linter and
the build may not install one, so this module implements the checks that
catch real bugs with near-zero false positives, over ast/tokenize only:

  unused-import      goimports analog: imported name never referenced
  mutable-default    def f(x=[]) / f(x={}) / f(x=set())
  bare-except        `except:` swallows KeyboardInterrupt/SystemExit
  fstring-no-field   f-string without any {placeholder}
  none-compare       `== None` / `!= None` instead of `is (not) None`
  nonascii-ident     asciicheck analog: non-ASCII identifiers
  duplicate-def      same name bound twice by def/class in one scope
  tab-indent         literal tabs in indentation (gofmt analog)
  metric-hygiene     Prometheus naming: snake_case, counters end _total,
                     histograms carry a unit suffix, gauges don't claim
                     _total, declared help strings are non-empty
  sleep-retry        `time.sleep(...)` inside a loop that handles
                     exceptions: an ad-hoc retry/reconnect loop.  Those
                     must use utils/retry.py's Backoff (jittered, capped,
                     reset-on-success); utils/retry.py itself is exempt
  readback-in-loop   `_readback(...)` / `device_get(...)` inside a loop:
                     a per-iteration device->host sync serializes the
                     host against the device once per token/slot — the
                     exact stall the engines' pipelined step_burst
                     exists to remove.  Only models/serve.py and
                     models/paged.py (the two engines, where the batched
                     readback lives) are exempt
  metric-docs        cross-file: every `tpu_serve_*` / `tpu_fleet_*` /
                     `tpu_disagg_*` metric declared in
                     models/ must carry non-empty help text at some
                     declaring site AND appear in ARCHITECTURE.md's
                     metric inventory — the serving metrics are the
                     fleet load-signal contract, and an undocumented
                     signal is one routers can't rely on

Suppress a line with ``# lint: ignore[<check>]`` or a whole file with
``# lint: skip-file`` in its first five lines.

Usage: python tools/lint.py PATH [PATH...]   (exit 1 on findings)
"""

from __future__ import annotations

import ast
import re
import sys
import tokenize
from pathlib import Path

IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([a-z-]+)\]")
SKIP_FILE_RE = re.compile(r"#\s*lint:\s*skip-file")

# Names whose import is a side effect or a re-export by convention.
SIDE_EFFECT_IMPORTS = {"__future__"}

# -- metric-hygiene (utils/metrics.py Registry call sites) -------------------
METRIC_KINDS = {"counter", "gauge", "histogram"}
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# Histograms observe a measured quantity; the name must say its unit.
HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_tokens", "_total")


def _metric_findings(kind: str, name: str, help_node) -> list[tuple[str, str]]:
    """Prometheus naming-convention verdicts for one registry call site.
    Returns (check, message) pairs; pure so tests can drive it directly."""
    out = []
    if not METRIC_NAME_RE.match(name):
        out.append(("metric-hygiene", f"metric name {name!r} is not snake_case"))
    if kind == "counter" and not name.endswith("_total"):
        out.append(("metric-hygiene", f"counter {name!r} must end in '_total'"))
    if kind == "gauge" and name.endswith("_total"):
        out.append((
            "metric-hygiene",
            f"gauge {name!r} must not end in '_total' (counters own that suffix)",
        ))
    if kind == "histogram" and not name.endswith(HISTOGRAM_SUFFIXES):
        out.append((
            "metric-hygiene",
            f"histogram {name!r} needs a unit suffix "
            f"({', '.join(HISTOGRAM_SUFFIXES)})",
        ))
    # Only an EXPLICIT empty literal is flagged: omitting help is the
    # lookup-by-name idiom (Registry returns the existing metric).
    if (
        isinstance(help_node, ast.Constant)
        and isinstance(help_node.value, str)
        and not help_node.value.strip()
    ):
        out.append(("metric-hygiene", f"metric {name!r} declared with empty help"))
    return out


class Finding:
    def __init__(self, path: Path, line: int, check: str, message: str):
        self.path, self.line, self.check, self.message = path, line, check, message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.check}: {self.message}"


def _ignored(source_lines: list[str], line: int, check: str) -> bool:
    if 1 <= line <= len(source_lines):
        m = IGNORE_RE.search(source_lines[line - 1])
        if m and m.group(1) == check:
            return True
    return False


class _ImportTracker(ast.NodeVisitor):
    """Collect imported bindings and every referenced name/attribute root."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # bound name -> (line, display)
        self.used: set[str] = set()
        self.string_annotations: list[str] = []

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name in SIDE_EFFECT_IMPORTS:
                continue
            bound = alias.asname or alias.name.split(".")[0]
            self.imports[bound] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module in SIDE_EFFECT_IMPORTS:
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            self.imports[bound] = (node.lineno, f"{node.module}.{alias.name}")

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        # only the root name matters for import usage
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        # string annotations / docstring references like "np.ndarray"
        if isinstance(node.value, str):
            self.string_annotations.append(node.value)


def check_file(path: Path) -> list[Finding]:
    source = path.read_text()
    lines = source.splitlines()
    for head in lines[:5]:
        if SKIP_FILE_RE.search(head):
            return []
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, "syntax", str(exc.msg))]

    findings: list[Finding] = []

    def add(line: int, check: str, message: str):
        if not _ignored(lines, line, check):
            findings.append(Finding(path, line, check, message))

    # ---- unused-import ----------------------------------------------------
    tracker = _ImportTracker()
    tracker.visit(tree)
    # names used inside string annotations ("np.ndarray") count as used
    annotation_blob = " ".join(tracker.string_annotations)
    is_package_init = path.name == "__init__.py"
    for bound, (line, display) in tracker.imports.items():
        if bound in tracker.used:
            continue
        if re.search(rf"\b{re.escape(bound)}\b", annotation_blob):
            continue
        if is_package_init:
            continue  # __init__ re-exports are the public surface
        if bound == "_":
            continue
        add(line, "unused-import", f"{display!r} imported but unused")

    # ---- AST-walk checks --------------------------------------------------
    # (name-set, flag-duplicates?) — duplicates are only flagged at module/
    # class level: function bodies legitimately redefine names across
    # early-return branches.
    scopes: list[tuple[set[str], bool]] = [(set(), True)]

    def walk(node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in [*node.args.defaults, *node.args.kw_defaults]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in {"list", "dict", "set"}
                    and not default.args
                    and not default.keywords
                ):
                    add(
                        default.lineno,
                        "mutable-default",
                        f"mutable default argument in {node.name}()",
                    )
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            add(node.lineno, "bare-except", "bare `except:` (catch Exception instead)")
        if isinstance(node, ast.JoinedStr):
            # Implicitly concatenated f-strings parse as nested/sibling
            # JoinedStr parts; only flag when the WHOLE expression has no
            # placeholder anywhere, and don't recurse (no double reports).
            if not any(isinstance(n, ast.FormattedValue) for n in ast.walk(node)):
                add(node.lineno, "fstring-no-field", "f-string without placeholders")
            return
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in METRIC_KINDS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            help_node = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"), None
            )
            for check, message in _metric_findings(
                node.func.attr, node.args[0].value, help_node
            ):
                add(node.lineno, check, message)
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and comp.value is None
                ):
                    add(node.lineno, "none-compare", "use `is None` / `is not None`")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            name = node.name
            if not name.isascii():
                add(node.lineno, "nonascii-ident", f"non-ASCII identifier {name!r}")
            scope, flag_dupes = scopes[-1]
            # decorated redefinitions (@overload, @property/setter) are legit
            if flag_dupes and name in scope and not node.decorator_list:
                add(node.lineno, "duplicate-def", f"{name!r} redefined in same scope")
            scope.add(name)
            scopes.append((set(), isinstance(node, ast.ClassDef)))
            for child in ast.iter_child_nodes(node):
                walk(child)
            scopes.pop()
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(tree)

    # ---- sleep-retry ------------------------------------------------------
    # A time.sleep inside a loop whose body also handles exceptions is the
    # signature of a hand-rolled retry/reconnect loop — exactly what
    # utils/retry.py's Backoff replaces (jitter, cap, reset-on-success,
    # observability).  The policy module itself implements the primitive.
    if not str(path).replace("\\", "/").endswith("utils/retry.py"):
        flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if not any(isinstance(n, ast.ExceptHandler) for n in ast.walk(node)):
                continue
            for n in ast.walk(node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "sleep"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "time"
                    and n.lineno not in flagged
                ):
                    flagged.add(n.lineno)
                    add(
                        n.lineno,
                        "sleep-retry",
                        "time.sleep in a retry/reconnect loop; "
                        "use utils.retry.Backoff",
                    )

    # ---- readback-in-loop -------------------------------------------------
    # A device->host readback inside a loop serializes host bookkeeping
    # against the device once per iteration — per token or per slot, the
    # stall the pipelined decode loop (models/serve.py step_burst) exists
    # to remove.  The two engines own the batched readback and are exempt;
    # everywhere else, hoist the readback out of the loop (read a stacked
    # trace once) or go through an engine.
    norm = str(path).replace("\\", "/")
    if not norm.endswith(("models/serve.py", "models/paged.py")):
        rb_flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            for n in ast.walk(node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("_readback", "device_get")
                    and n.lineno not in rb_flagged
                ):
                    rb_flagged.add(n.lineno)
                    add(
                        n.lineno,
                        "readback-in-loop",
                        f"{n.func.attr}() inside a loop syncs device->host "
                        "per iteration; batch the readback outside the loop",
                    )

    # ---- token-level checks ----------------------------------------------
    try:
        with tokenize.open(path) as fh:
            for tok in tokenize.generate_tokens(fh.readline):
                if tok.type == tokenize.INDENT and "\t" in tok.string:
                    add(tok.start[0], "tab-indent", "tab in indentation")
    except (tokenize.TokenError, SyntaxError):
        pass  # ast.parse above is the authority on syntax findings

    return findings


def check_metric_docs(paths: list[Path], arch_text: str) -> list[Finding]:
    """Cross-file check: every ``tpu_serve_*`` / ``tpu_fleet_*`` /
    ``tpu_disagg_*`` metric declared in models/ must (a) carry non-empty
    help text at at least one declaring site and (b) appear in
    ARCHITECTURE.md (the metric inventory / telemetry section).  Pure over
    its inputs so tests can drive it with synthetic trees and doc text."""
    # metric name -> list of (path, line, has_help)
    sites: dict[str, list[tuple[Path, int, bool]]] = {}
    for path in paths:
        norm = str(path).replace("\\", "/")
        if "/models/" not in norm and not norm.startswith("models/"):
            continue
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except (SyntaxError, OSError):
            continue  # check_file already reports syntax findings
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_KINDS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith(
                    ("tpu_serve_", "tpu_fleet_", "tpu_disagg_")
                )
            ):
                continue
            help_node = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"), None
            )
            has_help = (
                isinstance(help_node, ast.Constant)
                and isinstance(help_node.value, str)
                and bool(help_node.value.strip())
            )
            sites.setdefault(node.args[0].value, []).append(
                (path, node.lineno, has_help)
            )

    findings: list[Finding] = []
    for name in sorted(sites):
        decls = sites[name]
        first_path, first_line, _ = decls[0]
        if not any(has_help for _, _, has_help in decls):
            findings.append(Finding(
                first_path, first_line, "metric-docs",
                f"serving metric {name!r} has no declaring site with help text",
            ))
        if name not in arch_text:
            findings.append(Finding(
                first_path, first_line, "metric-docs",
                f"serving metric {name!r} is not documented in ARCHITECTURE.md",
            ))
    return findings


def main(argv: list[str]) -> int:
    targets: list[Path] = []
    for arg in argv[1:] or ["k8s_dra_driver_tpu", "tests"]:
        p = Path(arg)
        if p.is_dir():
            targets.extend(sorted(p.rglob("*.py")))
        elif p.is_file() and p.suffix == ".py":
            targets.append(p)
        else:
            # A vanished/typo'd target must fail loudly, not lint nothing.
            print(f"lint: target {arg!r} is not a directory or .py file", file=sys.stderr)
            return 2
    targets = [t for t in targets if "proto/gen" not in str(t) and "__pycache__" not in str(t)]
    all_findings: list[Finding] = []
    for t in targets:
        all_findings.extend(check_file(t))
    arch = Path(__file__).resolve().parent.parent / "ARCHITECTURE.md"
    arch_text = arch.read_text() if arch.is_file() else ""
    all_findings.extend(check_metric_docs(targets, arch_text))
    for f in all_findings:
        print(f)
    print(
        f"lint: {len(targets)} files, {len(all_findings)} finding(s)",
        file=sys.stderr,
    )
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
