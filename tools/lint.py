#!/usr/bin/env python
"""First-party static analysis — the golangci-lint slot in CI.

The reference runs nine linters on every PR (.golangci.yaml:2-12,
.github/workflows/golang.yaml:27-49); this image bakes no Python linter and
the build may not install one, so this module implements the checks that
catch real bugs with near-zero false positives, over ast/tokenize only:

  unused-import      goimports analog: imported name never referenced
  mutable-default    def f(x=[]) / f(x={}) / f(x=set())
  bare-except        `except:` swallows KeyboardInterrupt/SystemExit
  fstring-no-field   f-string without any {placeholder}
  none-compare       `== None` / `!= None` instead of `is (not) None`
  nonascii-ident     asciicheck analog: non-ASCII identifiers
  duplicate-def      same name bound twice by def/class in one scope
  tab-indent         literal tabs in indentation (gofmt analog)
  metric-hygiene     Prometheus naming: snake_case, counters end _total,
                     histograms carry a unit suffix, gauges don't claim
                     _total, declared help strings are non-empty
  sleep-retry        `time.sleep(...)` inside a loop that handles
                     exceptions: an ad-hoc retry/reconnect loop.  Those
                     must use utils/retry.py's Backoff (jittered, capped,
                     reset-on-success); utils/retry.py itself is exempt
  readback-in-loop   `_readback(...)` / `device_get(...)` inside a loop:
                     a per-iteration device->host sync serializes the
                     host against the device once per token/slot — the
                     exact stall the engines' pipelined step_burst
                     exists to remove.  Only models/serve.py and
                     models/paged.py (the two engines, where the batched
                     readback lives) are exempt
  metric-docs        cross-file: every `tpu_serve_*` / `tpu_fleet_*` /
                     `tpu_disagg_*` / `tpu_transport_*` metric declared in
                     models/ — plus the scheduler observability surface
                     (`dra_plan_*` / `dra_gang_*` / `dra_sim_*` /
                     `dra_extender_*`) wherever declared — must carry
                     non-empty help text at some declaring site AND
                     appear in ARCHITECTURE.md's metric inventory — the
                     serving metrics are the fleet load-signal contract,
                     and an undocumented signal is one routers and
                     dashboards can't rely on
  metric-labels      cross-file: label keys at `tpu_serve_*` /
                     `tpu_fleet_*` / `tpu_disagg_*` / `tpu_transport_*` /
                     `dra_*` metric call sites must come from the closed
                     vocabulary
                     (METRIC_LABEL_KEYS), and label values must not be
                     f-strings / str.format — request-unique label
                     values are unbounded cardinality, the classic
                     Prometheus OOM

Whole-program passes (lock-discipline, jit-purity, terminal-funnel,
block-accounting) live in tools/analysis/ and run via ``--analyze``
against tools/analysis/baseline.json; see that package's docstring.

Suppress a line with ``# lint: ignore[<check>]`` or a whole file with
``# lint: skip-file`` in its first five lines.

Usage: python tools/lint.py [--changed] [--json] PATH [PATH...]
       python tools/lint.py --analyze [--json|--write-baseline] [PATH...]
(exit 1 on findings, 2 on a bad target)
"""

from __future__ import annotations

import ast
import json
import re
import subprocess
import sys
import tokenize
from pathlib import Path

IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([a-z-]+)\]")
SKIP_FILE_RE = re.compile(r"#\s*lint:\s*skip-file")

# Names whose import is a side effect or a re-export by convention.
SIDE_EFFECT_IMPORTS = {"__future__"}

# -- metric-hygiene (utils/metrics.py Registry call sites) -------------------
METRIC_KINDS = {"counter", "gauge", "histogram"}
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# Histograms observe a measured quantity; the name must say its unit.
HISTOGRAM_SUFFIXES = ("_seconds", "_bytes", "_tokens", "_total")


def _metric_findings(kind: str, name: str, help_node) -> list[tuple[str, str]]:
    """Prometheus naming-convention verdicts for one registry call site.
    Returns (check, message) pairs; pure so tests can drive it directly."""
    out = []
    if not METRIC_NAME_RE.match(name):
        out.append(("metric-hygiene", f"metric name {name!r} is not snake_case"))
    if kind == "counter" and not name.endswith("_total"):
        out.append(("metric-hygiene", f"counter {name!r} must end in '_total'"))
    if kind == "gauge" and name.endswith("_total"):
        out.append((
            "metric-hygiene",
            f"gauge {name!r} must not end in '_total' (counters own that suffix)",
        ))
    if kind == "histogram" and not name.endswith(HISTOGRAM_SUFFIXES):
        out.append((
            "metric-hygiene",
            f"histogram {name!r} needs a unit suffix "
            f"({', '.join(HISTOGRAM_SUFFIXES)})",
        ))
    # Only an EXPLICIT empty literal is flagged: omitting help is the
    # lookup-by-name idiom (Registry returns the existing metric).
    if (
        isinstance(help_node, ast.Constant)
        and isinstance(help_node.value, str)
        and not help_node.value.strip()
    ):
        out.append(("metric-hygiene", f"metric {name!r} declared with empty help"))
    return out


class Finding:
    def __init__(self, path: Path, line: int, check: str, message: str):
        self.path, self.line, self.check, self.message = path, line, check, message

    def __str__(self):
        return f"{self.path}:{self.line}: {self.check}: {self.message}"


def _ignored(source_lines: list[str], line: int, check: str) -> bool:
    if 1 <= line <= len(source_lines):
        m = IGNORE_RE.search(source_lines[line - 1])
        if m and m.group(1) == check:
            return True
    return False


class _ImportTracker(ast.NodeVisitor):
    """Collect imported bindings and every referenced name/attribute root."""

    def __init__(self):
        self.imports: dict[str, tuple[int, str]] = {}  # bound name -> (line, display)
        self.used: set[str] = set()
        self.string_annotations: list[str] = []

    def visit_Import(self, node: ast.Import):
        for alias in node.names:
            if alias.name in SIDE_EFFECT_IMPORTS:
                continue
            bound = alias.asname or alias.name.split(".")[0]
            self.imports[bound] = (node.lineno, alias.name)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module in SIDE_EFFECT_IMPORTS:
            return
        for alias in node.names:
            if alias.name == "*":
                continue
            bound = alias.asname or alias.name
            self.imports[bound] = (node.lineno, f"{node.module}.{alias.name}")

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        # only the root name matters for import usage
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant):
        # string annotations / docstring references like "np.ndarray"
        if isinstance(node.value, str):
            self.string_annotations.append(node.value)


def check_file(path: Path) -> list[Finding]:
    source = path.read_text()
    lines = source.splitlines()
    for head in lines[:5]:
        if SKIP_FILE_RE.search(head):
            return []
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [Finding(path, exc.lineno or 0, "syntax", str(exc.msg))]

    findings: list[Finding] = []

    def add(line: int, check: str, message: str):
        if not _ignored(lines, line, check):
            findings.append(Finding(path, line, check, message))

    # ---- unused-import ----------------------------------------------------
    tracker = _ImportTracker()
    tracker.visit(tree)
    # names used inside string annotations ("np.ndarray") count as used
    annotation_blob = " ".join(tracker.string_annotations)
    is_package_init = path.name == "__init__.py"
    for bound, (line, display) in tracker.imports.items():
        if bound in tracker.used:
            continue
        if re.search(rf"\b{re.escape(bound)}\b", annotation_blob):
            continue
        if is_package_init:
            continue  # __init__ re-exports are the public surface
        if bound == "_":
            continue
        add(line, "unused-import", f"{display!r} imported but unused")

    # ---- AST-walk checks --------------------------------------------------
    # (name-set, flag-duplicates?) — duplicates are only flagged at module/
    # class level: function bodies legitimately redefine names across
    # early-return branches.
    scopes: list[tuple[set[str], bool]] = [(set(), True)]

    def walk(node: ast.AST):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for default in [*node.args.defaults, *node.args.kw_defaults]:
                if isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in {"list", "dict", "set"}
                    and not default.args
                    and not default.keywords
                ):
                    add(
                        default.lineno,
                        "mutable-default",
                        f"mutable default argument in {node.name}()",
                    )
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            add(node.lineno, "bare-except", "bare `except:` (catch Exception instead)")
        if isinstance(node, ast.JoinedStr):
            # Implicitly concatenated f-strings parse as nested/sibling
            # JoinedStr parts; only flag when the WHOLE expression has no
            # placeholder anywhere, and don't recurse (no double reports).
            if not any(isinstance(n, ast.FormattedValue) for n in ast.walk(node)):
                add(node.lineno, "fstring-no-field", "f-string without placeholders")
            return
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in METRIC_KINDS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            help_node = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"), None
            )
            for check, message in _metric_findings(
                node.func.attr, node.args[0].value, help_node
            ):
                add(node.lineno, check, message)
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if (
                    isinstance(op, (ast.Eq, ast.NotEq))
                    and isinstance(comp, ast.Constant)
                    and comp.value is None
                ):
                    add(node.lineno, "none-compare", "use `is None` / `is not None`")
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            name = node.name
            if not name.isascii():
                add(node.lineno, "nonascii-ident", f"non-ASCII identifier {name!r}")
            scope, flag_dupes = scopes[-1]
            # decorated redefinitions (@overload, @property/setter) are legit
            if flag_dupes and name in scope and not node.decorator_list:
                add(node.lineno, "duplicate-def", f"{name!r} redefined in same scope")
            scope.add(name)
            scopes.append((set(), isinstance(node, ast.ClassDef)))
            for child in ast.iter_child_nodes(node):
                walk(child)
            scopes.pop()
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(tree)

    # ---- sleep-retry ------------------------------------------------------
    # A time.sleep inside a loop whose body also handles exceptions is the
    # signature of a hand-rolled retry/reconnect loop — exactly what
    # utils/retry.py's Backoff replaces (jitter, cap, reset-on-success,
    # observability).  The policy module itself implements the primitive.
    if not str(path).replace("\\", "/").endswith("utils/retry.py"):
        flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            if not any(isinstance(n, ast.ExceptHandler) for n in ast.walk(node)):
                continue
            for n in ast.walk(node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "sleep"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "time"
                    and n.lineno not in flagged
                ):
                    flagged.add(n.lineno)
                    add(
                        n.lineno,
                        "sleep-retry",
                        "time.sleep in a retry/reconnect loop; "
                        "use utils.retry.Backoff",
                    )

    # ---- readback-in-loop -------------------------------------------------
    # A device->host readback inside a loop serializes host bookkeeping
    # against the device once per iteration — per token or per slot, the
    # stall the pipelined decode loop (models/serve.py step_burst) exists
    # to remove.  The two engines own the batched readback and are exempt;
    # everywhere else, hoist the readback out of the loop (read a stacked
    # trace once) or go through an engine.
    norm = str(path).replace("\\", "/")
    if not norm.endswith(("models/serve.py", "models/paged.py")):
        rb_flagged: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
                continue
            for n in ast.walk(node):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("_readback", "device_get")
                    and n.lineno not in rb_flagged
                ):
                    rb_flagged.add(n.lineno)
                    add(
                        n.lineno,
                        "readback-in-loop",
                        f"{n.func.attr}() inside a loop syncs device->host "
                        "per iteration; batch the readback outside the loop",
                    )

    # ---- token-level checks ----------------------------------------------
    try:
        with tokenize.open(path) as fh:
            for tok in tokenize.generate_tokens(fh.readline):
                if tok.type == tokenize.INDENT and "\t" in tok.string:
                    add(tok.start[0], "tab-indent", "tab in indentation")
    except (tokenize.TokenError, SyntaxError):
        pass  # ast.parse above is the authority on syntax findings

    return findings


def check_metric_docs(paths: list[Path], arch_text: str) -> list[Finding]:
    """Cross-file check: every ``tpu_serve_*`` / ``tpu_fleet_*`` /
    ``tpu_disagg_*`` metric declared in models/ — and every scheduler
    observability metric (``dra_plan_*`` / ``dra_gang_*`` / ``dra_sim_*``
    / ``dra_extender_*``) wherever declared — must (a) carry non-empty
    help text at at least one declaring site and (b) appear in
    ARCHITECTURE.md (the metric inventory / telemetry section).  Pure over
    its inputs so tests can drive it with synthetic trees and doc text."""
    # metric name -> list of (path, line, has_help)
    sites: dict[str, list[tuple[Path, int, bool]]] = {}
    for path in paths:
        norm = str(path).replace("\\", "/")
        in_models = "/models/" in norm or norm.startswith("models/")
        # Serving metrics (tpu_*) live in models/; the scheduler/simulator
        # observability surface (PR 15) is policed wherever it is declared.
        prefixes = (
            "dra_plan_", "dra_gang_", "dra_sim_", "dra_extender_",
            "dra_sched_",
        )
        if in_models:
            prefixes += (
                "tpu_serve_", "tpu_fleet_", "tpu_disagg_",
                "tpu_autoscale_", "tpu_transport_",
                "tpu_obs_", "tpu_slo_",
            )
        try:
            tree = ast.parse(path.read_text(), filename=str(path))
        except (SyntaxError, OSError):
            continue  # check_file already reports syntax findings
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_KINDS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith(prefixes)
            ):
                continue
            help_node = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"), None
            )
            has_help = (
                isinstance(help_node, ast.Constant)
                and isinstance(help_node.value, str)
                and bool(help_node.value.strip())
            )
            sites.setdefault(node.args[0].value, []).append(
                (path, node.lineno, has_help)
            )

    findings: list[Finding] = []
    for name in sorted(sites):
        decls = sites[name]
        first_path, first_line, _ = decls[0]
        if not any(has_help for _, _, has_help in decls):
            findings.append(Finding(
                first_path, first_line, "metric-docs",
                f"serving metric {name!r} has no declaring site with help text",
            ))
        if name not in arch_text:
            findings.append(Finding(
                first_path, first_line, "metric-docs",
                f"serving metric {name!r} is not documented in ARCHITECTURE.md",
            ))
    return findings


# -- metric-labels (cross-file cardinality guard) ----------------------------
# Every label key the serving/control-plane metrics may use.  A new key is a
# contract change: dashboards, the fleet load-signal consumers, and the
# cardinality budget all see it — extend the vocabulary deliberately, here.
METRIC_LABEL_KEYS = frozenset({
    "status", "kind", "reason", "outcome", "stage", "state",
    "op", "node", "endpoint", "to", "section",
    # fault-injection dimensions (utils/faults.py): profile names and fault
    # kinds are both bounded, operator-declared sets
    "profile", "fault",
    # autoscaler scaling events (models/autoscaler.py): direction is the
    # closed {up, down, move} set
    "direction",
    # interconnect channel set (models/disagg.py ChannelSet): channel names
    # come from the topology daemon's published link list — an operator-
    # declared, bounded set, same cardinality class as endpoint/node
    "channel",
    # multi-objective plan scoring (scheduler/objectives.py): objective
    # names are the closed PlanScore component set
    "objective",
    # observability plane (models/obs_plane.py): burn-rate windows and
    # request tiers are closed sets declared in obs_plane; TELEM byte
    # direction reuses the existing "direction" key with the {tx, rx} set
    "window", "tier",
    # federation identity: instance names come from operator-declared
    # worker configs (same cardinality class as node/endpoint)
    "instance",
    # paged KV data plane (models/paged.py): pool dtype is the closed
    # {bf16/f32 names, int8, int4} set — tpu_serve_kv_bytes{dtype=} splits
    # resident pool bytes by quantization format, never per-request
    "dtype",
    # multi-scheduler contention harness (scheduler/cluster_sim.py):
    # scheduler labels are the bounded "sched-<idx>" set, one per racing
    # scheduler thread (N <= 8 in every harness config), precomputed at
    # worker construction — never formatted at the call site
    "scheduler",
    # fleet prefix-cache tier (models/fleet_prefix.py): hit provenance is
    # the closed {local, remote} set — tpu_fleet_prefix_hits_total{source=}
    # splits reuse by where the KV came from, never by prefix identity.
    # The gossip/pull planes reuse the existing "outcome" key with closed
    # sets: tpu_fleet_prefix_pub_total{outcome=} takes {shipped, shed,
    # ingested, withdrawn, fenced, decode_drop} (publisher shipping vs
    # supervisor ingest verdicts), and
    # tpu_fleet_prefix_pull_admission_total{outcome=} takes {admitted,
    # refused, bypass} (the KV-demand ledger's pull-window verdicts)
    "source",
})
METRIC_LABEL_PREFIXES = (
    "tpu_serve_", "tpu_fleet_", "tpu_disagg_", "tpu_autoscale_",
    "tpu_transport_", "tpu_obs_", "tpu_slo_", "dra_",
)
_METRIC_CALL_ATTRS = {"inc", "observe", "set"}
# First positionals of Counter.inc/Histogram.observe/Gauge.set when passed by
# keyword; not labels.
_NON_LABEL_KWARGS = {"amount", "value", "help"}


def check_metric_labels(paths: list[Path]) -> list[Finding]:
    """Cross-file: resolve metric variables (``_M_X = REGISTRY.counter("…")``)
    to their metric names, then police every ``_M_X.inc/observe/set`` call
    site: label keys must come from METRIC_LABEL_KEYS and label values must
    not be f-strings or ``.format(...)`` — a per-request label value is
    unbounded time-series cardinality."""
    var_to_metric: dict[str, str] = {}
    parsed: list[tuple[Path, ast.Module, list[str]]] = []
    for path in paths:
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (SyntaxError, OSError):
            continue  # check_file already reports syntax findings
        lines = source.splitlines()
        if any(SKIP_FILE_RE.search(h) for h in lines[:5]):
            continue
        parsed.append((path, tree, lines))
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr in METRIC_KINDS
                and node.value.args
                and isinstance(node.value.args[0], ast.Constant)
                and isinstance(node.value.args[0].value, str)
            ):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    var_to_metric[tgt.id] = node.value.args[0].value

    findings: list[Finding] = []
    for path, tree, lines in parsed:
        def add(line: int, message: str) -> None:
            if not _ignored(lines, line, "metric-labels"):
                findings.append(Finding(path, line, "metric-labels", message))

        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_CALL_ATTRS
            ):
                continue
            base = node.func.value
            var = base.id if isinstance(base, ast.Name) else (
                base.attr if isinstance(base, ast.Attribute) else None
            )
            metric = var_to_metric.get(var or "")
            if metric is None or not metric.startswith(METRIC_LABEL_PREFIXES):
                continue
            for kw in node.keywords:
                if kw.arg is None:
                    add(
                        node.lineno,
                        f"metric {metric!r}: **kwargs label expansion hides the "
                        "label keys from this check; pass labels explicitly",
                    )
                    continue
                if kw.arg in _NON_LABEL_KWARGS:
                    continue
                if kw.arg not in METRIC_LABEL_KEYS:
                    add(
                        node.lineno,
                        f"metric {metric!r}: label key {kw.arg!r} is not in the "
                        "closed vocabulary (lint.METRIC_LABEL_KEYS); extend it "
                        "deliberately or rename the label",
                    )
                value = kw.value
                if isinstance(value, ast.JoinedStr):
                    add(
                        node.lineno,
                        f"metric {metric!r}: f-string value for label {kw.arg!r} "
                        "is unbounded cardinality; use a small closed set",
                    )
                elif (
                    isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr == "format"
                ):
                    add(
                        node.lineno,
                        f"metric {metric!r}: .format() value for label {kw.arg!r} "
                        "is unbounded cardinality; use a small closed set",
                    )
    return findings


# -- CLI ---------------------------------------------------------------------

_KNOWN_FLAGS = {"--analyze", "--changed", "--json", "--write-baseline"}


def changed_files(repo_root: Path) -> list[Path] | None:
    """Tracked .py files differing from ``git merge-base HEAD main``, plus
    untracked ones.  None when git can't answer (CI shallow clone, detached
    tree without main, …) — the caller falls back to a full run."""
    def git(*args: str) -> str:
        return subprocess.run(
            ["git", *args],
            capture_output=True,
            text=True,
            cwd=repo_root,
            check=True,
        ).stdout

    try:
        base = git("merge-base", "HEAD", "main").strip()
        names = git("diff", "--name-only", base, "--").splitlines()
        names += git("ls-files", "--others", "--exclude-standard").splitlines()
    except (subprocess.CalledProcessError, OSError):
        return None
    out: list[Path] = []
    for name in names:
        if not name.endswith(".py"):
            continue
        p = repo_root / name
        if p.is_file():
            out.append(p)
    return sorted(set(out))


def _run_analyze(positional: list[str], as_json: bool, write_baseline: bool) -> int:
    sys.path.insert(0, str(Path(__file__).resolve().parent))
    from analysis import findings as _findings  # tools/ on sys.path -> tools/analysis/
    from analysis import runner as _runner

    repo_root = Path(__file__).resolve().parent.parent
    paths: list[Path] = []
    for arg in positional or ["k8s_dra_driver_tpu"]:
        p = Path(arg)
        if not (p.is_dir() or (p.is_file() and p.suffix == ".py")):
            print(f"lint: target {arg!r} is not a directory or .py file", file=sys.stderr)
            return 2
        paths.append(p)

    if write_baseline:
        report = _runner.run_analysis(paths, baseline_path=None, root=repo_root)
        _findings.write_baseline(report.result.new, _runner.DEFAULT_BASELINE)
        print(
            f"analysis: wrote {len(report.result.new)} finding(s) to "
            f"{_runner.DEFAULT_BASELINE}",
            file=sys.stderr,
        )
        return 0

    report = _runner.run_analysis(
        paths, baseline_path=_runner.DEFAULT_BASELINE, root=repo_root
    )
    if as_json:
        print(json.dumps(report.to_json(), indent=2))
    else:
        for f in report.result.baselined:
            print(f.render(baselined=True))
        for f in report.result.new:
            print(f.render())
    for key in report.result.stale:
        print(
            f"analysis: stale baseline entry {key!r} (no matching finding; "
            "delete it from baseline.json)",
            file=sys.stderr,
        )
    print(
        f"analysis: {report.files} files, {len(report.result.new)} new finding(s), "
        f"{len(report.result.baselined)} baselined, "
        f"{len(report.result.stale)} stale baseline entr(y/ies)",
        file=sys.stderr,
    )
    return 1 if report.failed else 0


def main(argv: list[str]) -> int:
    args = argv[1:]
    flags = {a for a in args if a.startswith("--")}
    unknown = flags - _KNOWN_FLAGS
    if unknown:
        print(f"lint: unknown flag(s) {sorted(unknown)}", file=sys.stderr)
        return 2
    positional = [a for a in args if not a.startswith("--")]
    as_json = "--json" in flags

    if "--analyze" in flags:
        return _run_analyze(positional, as_json, "--write-baseline" in flags)

    targets: list[Path] = []
    for arg in positional or ["k8s_dra_driver_tpu", "tests"]:
        p = Path(arg)
        if p.is_dir():
            targets.extend(sorted(p.rglob("*.py")))
        elif p.is_file() and p.suffix == ".py":
            targets.append(p)
        else:
            # A vanished/typo'd target must fail loudly, not lint nothing.
            print(f"lint: target {arg!r} is not a directory or .py file", file=sys.stderr)
            return 2
    targets = [t for t in targets if "proto/gen" not in str(t) and "__pycache__" not in str(t)]

    if "--changed" in flags:
        repo_root = Path(__file__).resolve().parent.parent
        changed = changed_files(repo_root)
        if changed is None:
            print("lint: --changed could not resolve merge-base; full run", file=sys.stderr)
        else:
            resolved = {t.resolve() for t in targets}
            targets = [c for c in changed if c.resolve() in resolved]

    all_findings: list[Finding] = []
    for t in targets:
        all_findings.extend(check_file(t))
    arch = Path(__file__).resolve().parent.parent / "ARCHITECTURE.md"
    arch_text = arch.read_text() if arch.is_file() else ""
    all_findings.extend(check_metric_docs(targets, arch_text))
    all_findings.extend(check_metric_labels(targets))
    if as_json:
        print(json.dumps(
            [
                {"path": str(f.path), "line": f.line, "check": f.check, "message": f.message}
                for f in all_findings
            ],
            indent=2,
        ))
    else:
        for f in all_findings:
            print(f)
    print(
        f"lint: {len(targets)} files, {len(all_findings)} finding(s)",
        file=sys.stderr,
    )
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
