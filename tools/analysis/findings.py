"""Finding record + baseline workflow for the whole-program analyzer.

A baseline entry is keyed on ``(check, path, symbol)`` — *not* on line
numbers — so unrelated edits that shift lines don't invalidate it.  A
key suppresses every current finding that matches it (those are still
printed, marked ``[baseline]``, but don't fail the run); a key that no
longer matches anything is *stale* and reported so it can be deleted.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Tuple


@dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    check: str
    symbol: str  # dotted enclosing scope, e.g. "PagedEngine.submit"
    message: str

    @property
    def key(self) -> str:
        return f"{self.check}::{self.path}::{self.symbol}"

    def to_json(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "line": self.line,
            "check": self.check,
            "symbol": self.symbol,
            "message": self.message,
        }

    def render(self, baselined: bool = False) -> str:
        tag = " [baseline]" if baselined else ""
        return f"{self.path}:{self.line}: [{self.check}]{tag} {self.symbol}: {self.message}"


@dataclass
class BaselineResult:
    """Partition of a run's findings against the checked-in baseline."""

    new: List[Finding] = field(default_factory=list)
    baselined: List[Finding] = field(default_factory=list)
    stale: List[str] = field(default_factory=list)  # keys with no live finding


def load_baseline(path: Path) -> List[str]:
    """Read baseline keys from ``path`` (missing file == empty baseline).

    Schema: ``{"version": 1, "entries": [{"check":…, "path":…, "symbol":…,
    "reason":…?}, …]}``.  ``reason`` is for humans and ignored here.
    """
    if not path.exists():
        return []
    data = json.loads(path.read_text(encoding="utf-8"))
    keys: List[str] = []
    for entry in data.get("entries", []):
        keys.append(f"{entry['check']}::{entry['path']}::{entry['symbol']}")
    return keys


def apply_baseline(findings: Iterable[Finding], keys: Iterable[str]) -> BaselineResult:
    keyset = set(keys)
    result = BaselineResult()
    seen: set = set()
    for f in sorted(findings):
        if f.key in keyset:
            result.baselined.append(f)
            seen.add(f.key)
        else:
            result.new.append(f)
    result.stale = sorted(keyset - seen)
    return result


def write_baseline(findings: Iterable[Finding], path: Path) -> None:
    """Serialize current findings as a fresh baseline (``--write-baseline``)."""
    seen: set = set()
    entries = []
    for f in sorted(findings):
        parts: Tuple[str, str, str] = (f.check, f.path, f.symbol)
        if parts in seen:
            continue
        seen.add(parts)
        entries.append({"check": f.check, "path": f.path, "symbol": f.symbol})
    path.write_text(
        json.dumps({"version": 1, "entries": entries}, indent=2) + "\n",
        encoding="utf-8",
    )
