"""lock-discipline pass.

Per class: any attribute *written* inside a ``with self.<lock>:`` block
(outside ``__init__``/``__post_init__``) joins the class's guarded set.
Reading or writing a guarded attribute from code that does not hold the
lock is a finding — unless the accessing method is *lock-held-only*,
i.e. every intra-class call site already holds the lock (computed to a
fixpoint, so helper chains like ``pump -> _retire -> _free_slot`` under
one ``with`` don't false-positive).

A module-level twin covers the ``_SEQ = 0; _SEQ_LOCK = Lock()`` idiom:
globals written under a module-level lock must always be accessed under
it.

Lock attributes are discovered, not declared: anything used as a
``with self.X:`` context manager, or assigned from
``threading.Lock/RLock/Condition``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .findings import Finding
from .index import FuncNode, Module, ModuleIndex, dotted

CHECK = "lock-discipline"

_LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}

_MUTATORS = {
    "append",
    "extend",
    "add",
    "update",
    "insert",
    "remove",
    "discard",
    "clear",
    "pop",
    "popleft",
    "appendleft",
    "setdefault",
}

_INIT_METHODS = {"__init__", "__post_init__", "__new__"}


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.iter_modules():
        for cls in mod.classes.values():
            findings.extend(_check_class(mod, cls))
        findings.extend(_check_module_globals(mod))
    return findings


# ---------------------------------------------------------------- class scope


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


_CONTAINER_FACTORIES = {
    "list",
    "dict",
    "set",
    "deque",
    "collections.deque",
    "defaultdict",
    "collections.defaultdict",
    "OrderedDict",
    "collections.OrderedDict",
    "Counter",
    "collections.Counter",
}


def _container_fields(cls_node: ast.ClassDef) -> Set[str]:
    """Fields ever assigned a container literal/factory.  Only for these do
    mutator-method calls (``self.x.append(...)``) count as writes — calling
    ``.update(pod)`` on an API client or ``.clear()`` on a threading.Event
    is a thread-safe method call, not shared-state mutation."""
    fields: Set[str] = set()
    for node in ast.walk(cls_node):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None:
            continue
        is_container = isinstance(
            value, (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
        ) or (isinstance(value, ast.Call) and dotted(value.func) in _CONTAINER_FACTORIES)
        if not is_container:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is not None:
                fields.add(attr)
    return fields


def _lock_attrs(cls_node: ast.ClassDef) -> Set[str]:
    locks: Set[str] = set()
    for node in ast.walk(cls_node):
        if isinstance(node, ast.With):
            for item in node.items:
                attr = _self_attr(item.context_expr)
                if attr is not None:
                    locks.add(attr)
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if value is None:
                continue
            for sub in ast.walk(value):
                if isinstance(sub, ast.Call) and dotted(sub.func) in _LOCK_FACTORIES:
                    targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            locks.add(attr)
    return locks


def _under_lock(node: ast.AST, method: ast.AST, locks: Set[str]) -> bool:
    cur = getattr(node, "parent", None)
    while cur is not None and cur is not method:
        if isinstance(cur, ast.With):
            for item in cur.items:
                attr = _self_attr(item.context_expr)
                if attr in locks:
                    return True
        if isinstance(cur, FuncNode):  # nested def: its body runs later, unlocked
            return False
        cur = getattr(cur, "parent", None)
    return False


def _access_kind(attr_node: ast.Attribute, containers: Set[str]) -> str:
    """'write' for stores, del, container mutation on the attribute; else 'read'."""
    if isinstance(attr_node.ctx, (ast.Store, ast.Del)):
        return "write"
    parent = getattr(attr_node, "parent", None)
    if isinstance(parent, ast.Subscript) and isinstance(parent.ctx, (ast.Store, ast.Del)):
        return "write"
    if (
        attr_node.attr in containers
        and isinstance(parent, ast.Attribute)
        and parent.attr in _MUTATORS
        and isinstance(getattr(parent, "parent", None), ast.Call)
        and getattr(parent, "parent").func is parent
    ):
        return "write"
    if isinstance(parent, ast.AugAssign) and parent.target is attr_node:
        return "write"
    return "read"


def _check_class(mod: Module, cls) -> List[Finding]:
    locks = _lock_attrs(cls.node)
    if not locks:
        return []
    containers = _container_fields(cls.node)

    # (method, attr, line, kind, under) for every self.<attr> touch.
    accesses: List[Tuple[str, str, int, str, bool]] = []
    # Intra-class call sites: callee -> [(caller, under_lock)]
    callsites: Dict[str, List[Tuple[str, bool]]] = {}

    for name, meth in cls.methods.items():
        for node in ast.walk(meth.node):
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is None or attr in locks:
                    continue
                under = _under_lock(node, meth.node, locks)
                parent = getattr(node, "parent", None)
                if isinstance(parent, ast.Call) and parent.func is node and attr in cls.methods:
                    callsites.setdefault(attr, []).append((name, under))
                    continue
                accesses.append((name, attr, node.lineno, _access_kind(node, containers), under))

    guarded: Set[str] = {
        attr
        for (m, attr, _line, kind, under) in accesses
        if under and kind == "write" and m not in _INIT_METHODS
    }
    if not guarded:
        return []

    # Fixpoint: a method whose every intra-class call site holds the lock
    # (directly or via another lock-held method) inherits the lock context.
    # Call sites in __init__/__post_init__ are neutral — the object isn't
    # shared yet — so an init-only helper is held too.
    held: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for callee, sites in callsites.items():
            if callee in held:
                continue
            if sites and all(
                under or caller in held
                for caller, under in sites
                if caller not in _INIT_METHODS
            ):
                held.add(callee)
                changed = True

    findings: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for m, attr, line, kind, under in sorted(accesses, key=lambda a: a[2]):
        if attr not in guarded or under or m in _INIT_METHODS or m in held:
            continue
        if (m, attr) in reported:
            continue
        reported.add((m, attr))
        lock_names = "/".join(sorted(f"self.{l}" for l in locks))
        findings.append(
            Finding(
                path=mod.path,
                line=line,
                check=CHECK,
                symbol=f"{cls.name}.{m}",
                message=(
                    f"{kind} of self.{attr} without holding {lock_names} "
                    f"(field is written under the lock elsewhere in {cls.name})"
                ),
            )
        )
    return findings


# ------------------------------------------------------------- module scope


def _check_module_globals(mod: Module) -> List[Finding]:
    # Module-level lock names: X = threading.Lock() at module scope.
    locks: Set[str] = set()
    for stmt in mod.tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            if dotted(stmt.value.func) in _LOCK_FACTORIES:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        locks.add(tgt.id)
    if not locks:
        return []

    def owner_is(node: ast.AST, fn: ast.AST) -> bool:
        cur = getattr(node, "parent", None)
        while cur is not None:
            if isinstance(cur, FuncNode):
                return cur is fn
            cur = getattr(cur, "parent", None)
        return False

    def under(node: ast.AST, fn: ast.AST) -> bool:
        cur = getattr(node, "parent", None)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    if isinstance(item.context_expr, ast.Name) and item.context_expr.id in locks:
                        return True
            cur = getattr(cur, "parent", None)
        return False

    # Phase 1: the guarded set — globals written under a module lock
    # (writing a global from a function requires a `global` declaration).
    guarded: Set[str] = set()
    for rec in mod.all_functions:
        declared: Set[str] = set()
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Global):
                declared.update(node.names)
        if not declared:
            continue
        for node in ast.walk(rec.node):
            if (
                isinstance(node, ast.Name)
                and node.id in declared
                and isinstance(node.ctx, (ast.Store, ast.Del))
                and owner_is(node, rec.node)
                and under(node, rec.node)
            ):
                guarded.add(node.id)
    if not guarded:
        return []

    # Phase 2: every touch of a guarded global, from any function — readers
    # don't need a `global` declaration, so resolve local shadowing first.
    accesses: List[Tuple[str, str, int, str, bool]] = []  # (func, name, line, kind, under)
    for rec in mod.all_functions:
        declared = set()
        local: Set[str] = set()
        args = rec.node.args
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            local.add(a.arg)
        for a in (args.vararg, args.kwarg):
            if a is not None:
                local.add(a.arg)
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Global):
                declared.update(node.names)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                local.add(node.id)
        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Name) or node.id not in guarded:
                continue
            if not owner_is(node, rec.node):
                continue  # belongs to a nested def; scanned under its own record
            if node.id in local and node.id not in declared:
                continue  # shadowed by a true local of the same name
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            accesses.append((rec.qualname, node.id, node.lineno, kind, under(node, rec.node)))
    findings: List[Finding] = []
    reported: Set[Tuple[str, str]] = set()
    for fn, name, line, kind, u in sorted(accesses, key=lambda a: a[2]):
        if name not in guarded or u or (fn, name) in reported:
            continue
        reported.add((fn, name))
        findings.append(
            Finding(
                path=mod.path,
                line=line,
                check=CHECK,
                symbol=fn,
                message=(
                    f"{kind} of module global {name} without holding its lock "
                    f"({'/'.join(sorted(locks))})"
                ),
            )
        )
    return findings
