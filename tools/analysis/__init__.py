"""Whole-program invariant analyzer — the cross-function half of the
golangci-lint slot (tools/lint.py keeps the per-file checks and stays
the CLI front door: ``python tools/lint.py --analyze``).

Four class-aware passes run over one shared module index
(:mod:`index`):

  lock-discipline    per class, the fields WRITTEN inside ``with
                     self._lock:`` (any ``self.*_lock``) blocks form the
                     guarded set; reading or writing a guarded field from
                     a method outside the lock — unless every call site
                     of that method already holds the lock — is a data
                     race in waiting.  Module-level ``_LOCK``-guarded
                     globals get the same treatment.
  jit-purity         any function handed to ``jax.jit`` /
                     ``serve.shared_jit`` / ``lax.scan`` (resolved
                     through assignments and decorators, transitively
                     through local calls) must not call ``time.*`` /
                     ``random.*`` / ``print``, touch the journal or a
                     metric, or mutate a closed-over container — the
                     traced-side-effect bugs that break retrace caching
                     and bit-equality.
  terminal-funnel    constructing a ``Completion`` whose status is
                     terminal (deadline_exceeded/cancelled/quarantined/
                     shed/error) is only legal inside
                     ``serve._early_retire`` and functions carrying the
                     ``@terminal_retirer`` decorator; an error-text
                     Completion left at the default "ok" status is
                     flagged anywhere.
  block-accounting   in models/paged.py and models/disagg.py, blocks
                     acquired from a ``BlockAllocator`` (``.alloc`` /
                     ``.share``) must reach a ``.free`` or an ownership
                     sink on every raise/early-return edge of a
                     lightweight per-function CFG — the static twin of
                     the chaos suites' leak assertions.

Suppress one line with ``# lint: ignore[<pass>]``.  Pre-existing findings
live in ``tools/analysis/baseline.json`` (visible but not fatal until
burned down); anything NOT in the baseline fails the run.

Importable both as ``tools.analysis`` (repo root on sys.path) and as
``analysis`` (tools/ on sys.path, the tests' idiom) — submodules use
relative imports only.
"""

from .findings import Finding, load_baseline, apply_baseline  # noqa: F401
from .index import ModuleIndex  # noqa: F401
from .runner import PASSES, run_analysis  # noqa: F401
