"""block-accounting pass.

The static twin of the chaos suites' leak assertions: in the paged /
disagg engines (``*paged.py`` / ``*disagg.py``), KV blocks come from a
refcounted ``BlockAllocator`` and every acquisition (``.alloc(...)`` /
``.share(...)`` — or a call to a same-module function that *returns*
allocated blocks, e.g. ``_pick_slot``) must reach a release or an
ownership sink on **every** exit edge.

Abstract interpretation over a lightweight per-function CFG (document-
order statement stream with try/if structure):

* an ``Assign`` from an acquiring call mints a *token* bound to the
  assigned names; tuple-unpacking a block-returning call's result
  transfers the token to exactly the block-carrying tuple elements
  (derived from that function's ``return`` statement);
* a token *resolves* when a bound name is passed to any call
  (``.free(ids)``, ``self._finish(ids)``, ``list(ids)``…), stored into
  an attribute/subscript (``self._owned[slot] = ids``), or returned;
* between mint and resolution, any statement that can raise (contains a
  call) or exit early (``return`` / ``raise``) is a leaking edge —
  unless it sits in a ``try`` whose handlers/finally contain ``.free(``,
  or in an ``if <token> is None`` failure branch (no blocks on that
  path);
* except-handlers of the ``try`` that minted the token are exempt: when
  the acquiring statement itself raised, the token was never bound
  (``try: ids = a.alloc(n) except OutOfBlocks: a.free(hits)`` is the
  share-then-alloc idiom, not a leak);
* an acquiring call whose result is discarded outright is flagged
  immediately.

One finding per token, at the first leaking edge.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from .index import FuncNode, Module, ModuleIndex, dotted

CHECK = "block-accounting"

_SCOPE_SUFFIXES = ("paged.py", "disagg.py")
_ACQUIRE_ATTRS = {"alloc", "share"}


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.iter_modules():
        if not mod.path.endswith(_SCOPE_SUFFIXES):
            continue
        blockfns = _alloc_returning(mod)
        for rec in mod.all_functions:
            findings.extend(_check_function(mod, rec.node, rec.qualname, blockfns))
    return findings


# ----------------------------------------------------------- stream building


def _stmt_stream(fn: ast.AST) -> List[Tuple[ast.stmt, Sequence[ast.AST]]]:
    """Simple statements + compound-statement headers, in document order.

    Nested function/class bodies are excluded (they execute later, under
    their own record)."""
    out: List[Tuple[ast.stmt, Sequence[ast.AST]]] = []

    def visit(stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            if isinstance(s, ast.If):
                out.append((s, [s.test]))
                visit(s.body)
                visit(s.orelse)
            elif isinstance(s, ast.While):
                out.append((s, [s.test]))
                visit(s.body)
                visit(s.orelse)
            elif isinstance(s, (ast.For, ast.AsyncFor)):
                out.append((s, [s.iter]))
                visit(s.body)
                visit(s.orelse)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                out.append((s, [item.context_expr for item in s.items]))
                visit(s.body)
            elif isinstance(s, ast.Try):
                visit(s.body)
                for handler in s.handlers:
                    visit(handler.body)
                visit(s.orelse)
                visit(s.finalbody)
            elif isinstance(s, FuncNode + (ast.ClassDef,)):
                continue
            else:
                out.append((s, [s]))

    visit(fn.body)
    return out


# -------------------------------------------------------------------- tokens


@dataclass
class _Token:
    names: Set[str]
    line: int
    origin: str
    # For `picked = self._pick_slot(...)`: which tuple indices carry blocks
    # once `picked` is unpacked (None = the bound names carry blocks as-is).
    pending_indices: Optional[Set[int]] = None


def _acquire_call(node: ast.AST) -> Optional[ast.Call]:
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in _ACQUIRE_ATTRS
        ):
            return sub
    return None


def _target_names(target: ast.AST) -> Set[str]:
    names: Set[str] = set()
    if isinstance(target, ast.Name):
        names.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            names.update(_target_names(elt))
    elif isinstance(target, ast.Starred):
        names.update(_target_names(target.value))
    return names


def _alloc_returning(mod: Module) -> Dict[str, Optional[Set[int]]]:
    """name -> block-carrying return-tuple indices (None = whole value)."""
    result: Dict[str, Optional[Set[int]]] = {}
    for rec in mod.all_functions:
        token_names: Set[str] = set()
        for node in ast.walk(rec.node):
            if isinstance(node, ast.Assign) and _acquire_call(node.value) is not None:
                for tgt in node.targets:
                    token_names.update(_target_names(tgt))
        if not token_names:
            continue
        for node in ast.walk(rec.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            value = node.value
            if isinstance(value, ast.Tuple):
                indices = {
                    i
                    for i, elt in enumerate(value.elts)
                    if any(
                        isinstance(sub, ast.Name) and sub.id in token_names
                        for sub in ast.walk(elt)
                    )
                }
                if indices:
                    result[rec.name] = indices
            elif any(
                isinstance(sub, ast.Name) and sub.id in token_names
                for sub in ast.walk(value)
            ):
                result[rec.name] = None
    return result


# ----------------------------------------------------------- per-stmt checks


def _names_in(node: ast.AST, names: Set[str]) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id in names for sub in ast.walk(node))


def _direct_call_arg(node: ast.AST, names: Set[str]) -> bool:
    """Token name passed as a bare argument to any call inside ``node``."""
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        for arg in sub.args:
            if isinstance(arg, ast.Starred):
                arg = arg.value
            if isinstance(arg, ast.Name) and arg.id in names:
                return True
        for kw in sub.keywords:
            if isinstance(kw.value, ast.Name) and kw.value.id in names:
                return True
    return False


def _resolves(stmt: ast.stmt, exprs: Sequence[ast.AST], names: Set[str]) -> bool:
    if isinstance(stmt, ast.Return):
        return stmt.value is not None and _names_in(stmt.value, names)
    if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        if stmt.value is not None and _names_in(stmt.value, names):
            for tgt in targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    return True
    for expr in exprs:
        if _direct_call_arg(expr, names):
            return True
    return False


def _is_risky(stmt: ast.stmt, exprs: Sequence[ast.AST]) -> bool:
    if isinstance(stmt, (ast.Return, ast.Raise)):
        return True
    return any(isinstance(sub, ast.Call) for expr in exprs for sub in ast.walk(expr))


def _protected(stmt: ast.stmt, fn: ast.AST) -> bool:
    """Inside a try-body whose except/finally blocks release blocks."""
    cur: Optional[ast.AST] = stmt
    while cur is not None and cur is not fn:
        parent = getattr(cur, "parent", None)
        if isinstance(parent, ast.Try) and cur in parent.body:
            cleanup = list(parent.finalbody)
            for handler in parent.handlers:
                cleanup.extend(handler.body)
            for c in cleanup:
                for sub in ast.walk(c):
                    if (
                        isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Attribute)
                        and sub.func.attr == "free"
                    ):
                        return True
        cur = parent
    return False


def _acquire_trys(stmt: ast.stmt, fn: ast.AST) -> List[ast.Try]:
    """Every ``try`` whose body (transitively) contains the acquire."""
    trys: List[ast.Try] = []
    cur: Optional[ast.AST] = stmt
    while cur is not None and cur is not fn:
        parent = getattr(cur, "parent", None)
        if isinstance(parent, ast.Try) and cur in parent.body:
            trys.append(parent)
        cur = parent
    return trys


def _in_handler_of(stmt: ast.stmt, trys: List[ast.Try]) -> bool:
    cur: Optional[ast.AST] = stmt
    while cur is not None:
        parent = getattr(cur, "parent", None)
        if isinstance(parent, ast.ExceptHandler) and getattr(parent, "parent", None) in trys:
            return True
        cur = parent
    return False


def _in_failure_branch(stmt: ast.stmt, fn: ast.AST, names: Set[str]) -> bool:
    """Inside ``if <token> is None:`` / ``if not <token>:`` — no blocks held."""
    cur: Optional[ast.AST] = stmt
    while cur is not None and cur is not fn:
        parent = getattr(cur, "parent", None)
        if isinstance(parent, ast.If) and _is_failure_test(parent.test, names):
            body_contains = any(cur is s or _contains(s, cur) for s in parent.body)
            if body_contains:
                return True
        cur = parent
    return False


def _contains(root: ast.AST, target: ast.AST) -> bool:
    return any(sub is target for sub in ast.walk(root))


def _is_failure_test(test: ast.AST, names: Set[str]) -> bool:
    if (
        isinstance(test, ast.Compare)
        and isinstance(test.left, ast.Name)
        and test.left.id in names
        and len(test.ops) == 1
        and isinstance(test.ops[0], ast.Is)
        and isinstance(test.comparators[0], ast.Constant)
        and test.comparators[0].value is None
    ):
        return True
    if (
        isinstance(test, ast.UnaryOp)
        and isinstance(test.op, ast.Not)
        and isinstance(test.operand, ast.Name)
        and test.operand.id in names
    ):
        return True
    return False


# --------------------------------------------------------------- main driver


def _check_function(
    mod: Module,
    fn: ast.AST,
    symbol: str,
    blockfns: Dict[str, Optional[Set[int]]],
) -> List[Finding]:
    stream = _stmt_stream(fn)
    findings: List[Finding] = []

    # Collect acquisition events (stream position -> token).
    acquires: List[Tuple[int, _Token]] = []
    for i, (stmt, _exprs) in enumerate(stream):
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) and call.func.attr in _ACQUIRE_ATTRS:
                findings.append(
                    Finding(
                        path=mod.path,
                        line=stmt.lineno,
                        check=CHECK,
                        symbol=symbol,
                        message=(
                            f".{call.func.attr}(...) result discarded — acquired "
                            "blocks are unreachable and can never be freed"
                        ),
                    )
                )
            continue
        if not isinstance(stmt, ast.Assign):
            continue
        # Ownership sink right at the acquire: self.x = ....alloc(n)
        direct = _acquire_call(stmt.value)
        sink = all(isinstance(t, (ast.Attribute, ast.Subscript)) for t in stmt.targets)
        if direct is not None and not sink:
            names: Set[str] = set()
            for tgt in stmt.targets:
                names.update(_target_names(tgt))
            if names:
                acquires.append(
                    (i, _Token(names=names, line=stmt.lineno, origin=f".{direct.func.attr}(...)"))
                )
            continue
        # Call to a same-module block-returning function.
        if isinstance(stmt.value, ast.Call):
            callee = dotted(stmt.value.func)
            short = callee.split(".")[-1] if callee else None
            if short in blockfns and not sink:
                names = set()
                for tgt in stmt.targets:
                    names.update(_target_names(tgt))
                if names:
                    acquires.append(
                        (
                            i,
                            _Token(
                                names=names,
                                line=stmt.lineno,
                                origin=f"{short}(...)",
                                pending_indices=blockfns[short],
                            ),
                        )
                    )

    for start, token in acquires:
        _trace_token(mod, fn, symbol, stream, start, token, findings)
    return findings


def _trace_token(
    mod: Module,
    fn: ast.AST,
    symbol: str,
    stream: List[Tuple[ast.stmt, Sequence[ast.AST]]],
    start: int,
    token: _Token,
    findings: List[Finding],
) -> None:
    def leak(line: int, msg: str) -> None:
        findings.append(
            Finding(
                path=mod.path,
                line=line,
                check=CHECK,
                symbol=symbol,
                message=f"{msg} (blocks acquired via {token.origin} on line {token.line})",
            )
        )

    acquire_trys = _acquire_trys(stream[start][0], fn)
    for j in range(start + 1, len(stream)):
        stmt, exprs = stream[j]
        if _in_failure_branch(stmt, fn, token.names):
            continue
        if acquire_trys and _in_handler_of(stmt, acquire_trys):
            continue  # handler ran => the acquire raised => token never bound
        # Tuple-unpack of a block-returning call's result transfers the token.
        if (
            token.pending_indices is not None
            and isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Name)
            and stmt.value.id in token.names
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], (ast.Tuple, ast.List))
        ):
            elts = stmt.targets[0].elts
            carried: Set[str] = set()
            for idx in token.pending_indices:
                if idx < len(elts):
                    carried.update(_target_names(elts[idx]))
            if carried:
                token.names = carried
                token.pending_indices = None
                continue
        if _resolves(stmt, exprs, token.names):
            return
        if _is_risky(stmt, exprs):
            if _protected(stmt, fn):
                continue
            if isinstance(stmt, ast.Return):
                leak(stmt.lineno, "early return leaks acquired blocks")
            elif isinstance(stmt, ast.Raise):
                leak(stmt.lineno, "raise leaks acquired blocks")
            else:
                leak(
                    stmt.lineno,
                    "statement can raise while acquired blocks are unresolved "
                    "and no enclosing handler frees them",
                )
            return
    leak(token.line, "acquired blocks are never released, stored, or returned")
