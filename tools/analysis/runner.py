"""Pass orchestration: build the index once, run the passes, apply the
pragma escapes and the baseline, and shape ``--json`` output."""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from . import (
    admission_funnel,
    block_accounting,
    jit_purity,
    lock_discipline,
    terminal_funnel,
)
from .findings import BaselineResult, Finding, apply_baseline, load_baseline
from .index import ModuleIndex

PASSES = {
    lock_discipline.CHECK: lock_discipline.run,
    jit_purity.CHECK: jit_purity.run,
    terminal_funnel.CHECK: terminal_funnel.run,
    block_accounting.CHECK: block_accounting.run,
    admission_funnel.CHECK: admission_funnel.run,
}

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


@dataclass
class AnalysisReport:
    files: int
    result: BaselineResult

    @property
    def failed(self) -> bool:
        return bool(self.result.new)

    def to_json(self) -> Dict[str, object]:
        return {
            "version": 1,
            "files": self.files,
            "checks": sorted(PASSES),
            "findings": [f.to_json() for f in self.result.new],
            "baselined": [f.to_json() for f in self.result.baselined],
            "stale_baseline_keys": list(self.result.stale),
        }


def collect_files(targets: Iterable[Path]) -> List[Path]:
    files: List[Path] = []
    for target in targets:
        if target.is_dir():
            files.extend(
                p for p in sorted(target.rglob("*.py")) if "__pycache__" not in p.parts
            )
        elif target.suffix == ".py":
            files.append(target)
    return files


def run_analysis(
    targets: Iterable[Path],
    baseline_path: Optional[Path] = None,
    checks: Optional[Iterable[str]] = None,
    root: Optional[Path] = None,
) -> AnalysisReport:
    files = collect_files([Path(t) for t in targets])
    index = ModuleIndex.build(files, root=root)

    selected = set(checks) if checks is not None else set(PASSES)
    raw: List[Finding] = []
    for name, runner in PASSES.items():
        if name in selected:
            raw.extend(runner(index))

    kept: List[Finding] = []
    for f in raw:
        mod = index.modules.get(f.path)
        if mod is None:
            kept.append(f)
            continue
        if mod.skip or mod.ignored(f.line, f.check):
            continue
        kept.append(f)

    keys = load_baseline(baseline_path) if baseline_path is not None else []
    return AnalysisReport(files=len(files), result=apply_baseline(kept, keys))
