"""jit-purity pass.

Functions that get traced — passed to ``jax.jit`` / ``serve.shared_jit``
/ ``lax.scan`` (resolved through assignments and decorators, then
transitively through same-module calls) — run once at trace time, and
any side effect there silently detaches from execution: clocks and RNG
freeze into the compiled artifact, metrics/journal record once per
*compile* instead of per call, and mutating a closed-over container
desynchronizes host state from device state.  Exactly the retrace /
bit-equality bug class PRs 4–5 hit at runtime; this pass catches it at
lint time.

Flagged inside a traced function:

* calls to ``time.*``, ``random.*``, ``np.random.*``, ``print``
* journal / metrics / registry effects (``JOURNAL.*``, ``*.inc`` /
  ``*.observe``, ``.set``/``.labels`` on an ALL_CAPS global, ``REGISTRY.*``)
* mutation of a closed-over or global container (``xs.append(...)``,
  ``cache[k] = v`` where the base is not a local) — jnp's functional
  ``.at[i].set()`` is naturally exempt because its base is a Subscript.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .findings import Finding
from .index import FuncNode, Module, ModuleIndex, dotted

CHECK = "jit-purity"

_JIT_WRAPPERS = {
    "jax.jit",
    "jit",
    "shared_jit",
    "serve.shared_jit",
    "jax.lax.scan",
    "lax.scan",
    "scan",
    "jax.checkpoint",
}

_MUTATORS = {
    "append",
    "extend",
    "add",
    "update",
    "insert",
    "remove",
    "discard",
    "clear",
    "pop",
    "popleft",
    "appendleft",
    "setdefault",
}


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.iter_modules():
        findings.extend(_check_module(mod))
    return findings


def _decorated_jit(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        name = dotted(dec)
        if name in _JIT_WRAPPERS:
            return True
        if isinstance(dec, ast.Call):
            if dotted(dec.func) in _JIT_WRAPPERS:
                return True
            if dotted(dec.func) in {"partial", "functools.partial"} and any(
                dotted(a) in _JIT_WRAPPERS for a in dec.args
            ):
                return True
    return False


def _resolve_local(name: str, at: ast.AST, mod: Module) -> Optional[ast.AST]:
    """Resolve ``name`` to a FunctionDef/Lambda visible from ``at``.

    Walks enclosing scopes outward; at each scope follows direct
    ``def name`` children and one level of ``name = other`` aliasing.
    """
    seen: Set[str] = set()
    for _ in range(6):  # bounded alias chase
        if name in seen:
            return None
        seen.add(name)
        alias: Optional[str] = None
        scope: Optional[ast.AST] = at
        while scope is not None:
            if isinstance(scope, FuncNode) or isinstance(scope, ast.Module):
                for stmt in scope.body:
                    if isinstance(stmt, FuncNode) and stmt.name == name:
                        return stmt
                    if isinstance(stmt, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name for t in stmt.targets
                    ):
                        if isinstance(stmt.value, ast.Lambda):
                            return stmt.value
                        if isinstance(stmt.value, ast.Name):
                            alias = stmt.value.id
                if alias is not None:
                    break
            scope = getattr(scope, "parent", None)
        if alias is None:
            return None
        name = alias
    return None


def _traced_roots(mod: Module) -> List[Tuple[ast.AST, str]]:
    """(function node, why-traced) for every jit/scan entry in the module."""
    roots: List[Tuple[ast.AST, str]] = []
    for rec in mod.all_functions:
        if _decorated_jit(rec.node):
            roots.append((rec.node, f"decorated on line {rec.node.lineno}"))
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        wrapper = dotted(node.func)
        if wrapper not in _JIT_WRAPPERS or not node.args:
            continue
        target = node.args[0]
        why = f"passed to {wrapper} on line {node.lineno}"
        if isinstance(target, ast.Lambda):
            roots.append((target, why))
        elif isinstance(target, ast.Name):
            resolved = _resolve_local(target.id, node, mod)
            if resolved is not None:
                roots.append((resolved, why))
    return roots


def _local_names(fn: ast.AST) -> Set[str]:
    local: Set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        for a in args.posonlyargs + args.args + args.kwonlyargs:
            local.add(a.arg)
        for a in (args.vararg, args.kwarg):
            if a is not None:
                local.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            local.add(node.id)
        elif isinstance(node, FuncNode):
            local.add(node.name)
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    local.add(sub.id)
    return local


def _is_metric_root(root: str) -> bool:
    return root.isupper() or (root.startswith("_") and root.lstrip("_").isupper())


def _impurities(fn: ast.AST, mod: Module, why: str) -> List[Finding]:
    local = _local_names(fn)
    out: List[Finding] = []
    symbol = mod.symbol_for(fn) if not isinstance(fn, ast.Lambda) else mod.symbol_for(
        getattr(fn, "parent", fn)
    )

    def emit(line: int, msg: str) -> None:
        out.append(
            Finding(
                path=mod.path,
                line=line,
                check=CHECK,
                symbol=symbol,
                message=f"{msg} (traced: {why})",
            )
        )

    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name is None:
                continue
            root = name.split(".")[0]
            if name == "print":
                emit(node.lineno, "print() inside a traced function")
            elif name.startswith(("time.", "random.", "np.random.", "numpy.random.")):
                emit(node.lineno, f"call to {name}() inside a traced function")
            elif "JOURNAL" in name.split("."):
                emit(node.lineno, f"journal write {name}() inside a traced function")
            elif root == "REGISTRY":
                emit(node.lineno, f"registry call {name}() inside a traced function")
            elif name.endswith((".inc", ".observe")) or (
                name.endswith((".set", ".labels")) and _is_metric_root(root)
            ):
                emit(node.lineno, f"metric side effect {name}() inside a traced function")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS
                and root not in local
                # Result discarded => mutation idiom.  When the result is
                # used (`updates, st = opt.update(...)`) it's the functional
                # optax/jax style, which is pure.
                and isinstance(getattr(node, "parent", None), ast.Expr)
            ):
                emit(
                    node.lineno,
                    f"mutation of closed-over container {name}() inside a traced function",
                )
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Subscript):
                    base = dotted(tgt.value)
                    if base is not None and base.split(".")[0] not in local:
                        emit(
                            tgt.lineno,
                            f"subscript store into closed-over {base}[...] inside a traced function",
                        )
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            emit(node.lineno, "global/nonlocal rebinding inside a traced function")
    return out


def _check_module(mod: Module) -> List[Finding]:
    roots = _traced_roots(mod)
    if not roots:
        return []

    # Transitive closure over same-module calls: a helper called from a
    # traced function is traced too.
    queue: List[Tuple[ast.AST, str]] = list(roots)
    seen: Set[int] = set()
    findings: List[Finding] = []
    while queue:
        fn, why = queue.pop()
        if id(fn) in seen:
            continue
        seen.add(id(fn))
        findings.extend(_impurities(fn, mod, why))
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = _resolve_local(node.func.id, node, mod)
                if callee is not None and id(callee) not in seen:
                    queue.append((callee, f"called from traced code on line {node.lineno}"))
    # A traced function's own Finding lines can repeat via multiple roots.
    uniq = {(f.path, f.line, f.message): f for f in findings}
    return sorted(uniq.values())
