"""admission-funnel pass.

The KV-demand admission control in ``models/disagg.py`` is deadlock-proof
only if its two pieces of state move through their funnels:

* ``self._ledger`` — the decode-side block-reservation ledger.  Every
  commit/release must go through ``_ledger_commit``/``_ledger_release``
  (``__init__`` seeds the empty dict); a raw ``self._ledger[rid] = n``
  elsewhere can strand a reservation past the stream's life and starve
  admission forever, or double-release and over-admit into a wedge.
* ``self._admission_parked`` — the parked-handoff queue.  Only
  ``_park_admission`` (enqueue + gauge + journal), ``_unpark_admissions``
  (FIFO re-admit) and ``_deadlock_tick`` (forced drain) may mutate it;
  a stray ``append`` skips the ``tpu_disagg_admission_parked`` gauge and
  the journal record, so the deadlock detector and the operator both go
  blind to the parked stream.

This pass machine-checks both funnels: any mutation of either attribute
(attribute assign, subscript store/delete, augmented assign, or a call
to a mutating method like ``append``/``pop``/``update``) outside its
allowlisted methods is a finding.  Reads (``len``, ``.get``,
``.values``, iteration) are not mutations and stay legal everywhere.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .findings import Finding
from .index import FuncNode, ModuleIndex, dotted, enclosing

CHECK = "admission-funnel"

# attribute -> methods allowed to mutate it (anywhere in the tree rooted
# at that method, so helper closures inside a funnel stay legal).
FUNNELS = {
    "_ledger": frozenset({"__init__", "_ledger_commit", "_ledger_release"}),
    "_admission_parked": frozenset(
        {"__init__", "_park_admission", "_unpark_admissions", "_deadlock_tick"}
    ),
}

MUTATORS = frozenset(
    {
        "append", "extend", "insert", "remove", "clear", "pop", "popitem",
        "update", "setdefault", "sort", "reverse",
    }
)


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.iter_modules():
        for node in ast.walk(mod.tree):
            attr = _mutated_attr(node)
            if attr is None:
                continue
            if _in_funnel(node, FUNNELS[attr]):
                continue
            findings.append(
                Finding(
                    path=mod.path,
                    line=node.lineno,
                    check=CHECK,
                    symbol=mod.symbol_for(node),
                    message=(
                        f"self.{attr} mutated outside its admission funnel "
                        f"({', '.join(sorted(FUNNELS[attr]))}) — gauge, "
                        "journal and reservation accounting go out of sync"
                    ),
                )
            )
    return findings


def _self_attr(node: ast.AST) -> Optional[str]:
    """``_ledger``/``_admission_parked`` when node is that ``self.<attr>``."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
        and node.attr in FUNNELS
    ):
        return node.attr
    return None


def _target_attr(target: ast.AST) -> Optional[str]:
    """The funneled attribute a store/delete target reaches, if any:
    ``self.x``, ``self.x[i]``, or either inside a tuple unpack."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            attr = _target_attr(elt)
            if attr is not None:
                return attr
        return None
    if isinstance(target, (ast.Subscript, ast.Starred)):
        return _target_attr(target.value)
    return _self_attr(target)


def _mutated_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Assign):
        for target in node.targets:
            attr = _target_attr(target)
            if attr is not None:
                return attr
        return None
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return _target_attr(node.target)
    if isinstance(node, ast.Delete):
        for target in node.targets:
            attr = _target_attr(target)
            if attr is not None:
                return attr
        return None
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        name = dotted(node.func)
        if name is None:
            return None
        parts = name.split(".")
        # self.<attr>.<mutator>(...) — reads like .get/.values pass through
        if (
            len(parts) == 3
            and parts[0] == "self"
            and parts[1] in FUNNELS
            and parts[2] in MUTATORS
        ):
            return parts[1]
    return None


def _in_funnel(node: ast.AST, allowed: frozenset) -> bool:
    for fn in enclosing(node, FuncNode):
        if getattr(fn, "name", "") in allowed:
            return True
    return False
