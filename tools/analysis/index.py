"""Shared module index for the whole-program passes.

One parse of every target file, annotated with:

* parent links (``node.parent``) so passes can ask "am I inside a
  ``try`` that frees?" without re-walking,
* per-line ``# lint: ignore[check]`` pragmas and ``# lint: skip-file``,
* a symbol table of classes / methods / module functions with dotted
  qualnames (the stable half of a baseline key).

Passes receive the index and return ``Finding`` lists; they never read
files themselves.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

IGNORE_RE = re.compile(r"#\s*lint:\s*ignore\[([a-z-]+(?:\s*,\s*[a-z-]+)*)\]")
SKIP_FILE_RE = re.compile(r"#\s*lint:\s*skip-file")

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, None for anything else.

    Subscript bases (``x.at[i].set``) intentionally resolve to None —
    that is what exempts jnp's functional ``.at[].set()`` updates from
    the mutation checks.
    """
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base is not None else None
    return None


@dataclass
class FunctionRec:
    name: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    class_name: Optional[str] = None


@dataclass
class ClassRec:
    name: str
    node: ast.ClassDef
    methods: Dict[str, FunctionRec] = field(default_factory=dict)


@dataclass
class Module:
    path: str
    tree: ast.Module
    source: str
    skip: bool = False
    ignores: Dict[int, Set[str]] = field(default_factory=dict)
    functions: Dict[str, FunctionRec] = field(default_factory=dict)
    classes: Dict[str, ClassRec] = field(default_factory=dict)
    all_functions: List[FunctionRec] = field(default_factory=list)

    def ignored(self, line: int, check: str) -> bool:
        return check in self.ignores.get(line, set())

    def symbol_for(self, node: ast.AST) -> str:
        """Dotted enclosing scope (``Class.method`` / ``func`` / ``<module>``)."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, FuncNode + (ast.ClassDef,)):
                parts.append(cur.name)
            cur = getattr(cur, "parent", None)
        return ".".join(reversed(parts)) or "<module>"


class ModuleIndex:
    def __init__(self) -> None:
        self.modules: Dict[str, Module] = {}

    @classmethod
    def build(cls, files: List[Path], root: Optional[Path] = None) -> "ModuleIndex":
        idx = cls()
        for fp in files:
            rel = fp
            if root is not None:
                try:
                    rel = fp.resolve().relative_to(root.resolve())
                except ValueError:
                    rel = fp
            try:
                source = fp.read_text(encoding="utf-8")
                tree = ast.parse(source)
            except (OSError, SyntaxError, UnicodeDecodeError):
                continue  # compileall owns syntax errors; nothing for us here
            mod = Module(path=rel.as_posix(), tree=tree, source=source)
            _annotate(mod)
            idx.modules[mod.path] = mod
        return idx

    def module_endswith(self, suffix: str) -> Optional[Module]:
        for path, mod in self.modules.items():
            if path.endswith(suffix):
                return mod
        return None

    def iter_modules(self) -> Iterator[Module]:
        yield from self.modules.values()


def _annotate(mod: Module) -> None:
    for node in ast.walk(mod.tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]

    for i, raw in enumerate(mod.source.splitlines(), start=1):
        m = IGNORE_RE.search(raw)
        if m:
            checks = {c.strip() for c in m.group(1).split(",")}
            mod.ignores.setdefault(i, set()).update(checks)
        if i <= 5 and SKIP_FILE_RE.search(raw):
            mod.skip = True

    for node in ast.walk(mod.tree):
        if isinstance(node, FuncNode):
            qual = mod.symbol_for(node)
            cls_name = None
            parent = getattr(node, "parent", None)
            if isinstance(parent, ast.ClassDef):
                cls_name = parent.name
            rec = FunctionRec(name=node.name, qualname=qual, node=node, class_name=cls_name)
            mod.all_functions.append(rec)
            if isinstance(parent, ast.Module):
                mod.functions[node.name] = rec
        elif isinstance(node, ast.ClassDef) and isinstance(getattr(node, "parent", None), ast.Module):
            mod.classes[node.name] = ClassRec(name=node.name, node=node)

    for cls_rec in mod.classes.values():
        for stmt in cls_rec.node.body:
            if isinstance(stmt, FuncNode):
                cls_rec.methods[stmt.name] = FunctionRec(
                    name=stmt.name,
                    qualname=f"{cls_rec.name}.{stmt.name}",
                    node=stmt,
                    class_name=cls_rec.name,
                )


def enclosing(node: ast.AST, kinds: Tuple[type, ...]) -> Iterator[ast.AST]:
    """Yield ancestors of ``node`` (nearest first) that match ``kinds``."""
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            yield cur
        cur = getattr(cur, "parent", None)


def contains_call_attr(node: ast.AST, attrs: Set[str]) -> bool:
    """True if any ``X.attr(...)`` call with attr in ``attrs`` occurs in node."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in attrs
        ):
            return True
    return False
