"""terminal-funnel pass.

PR 5 funneled every terminal retirement through ``serve._early_retire``
so that slot frees, paged block refunds, journal records, and telemetry
all happen exactly once per request.  This pass machine-checks the
funnel: constructing a ``Completion`` whose ``status=`` is terminal
(``deadline_exceeded``/``cancelled``/``quarantined``/``shed``/``error``)
is only legal inside ``_early_retire`` itself or a function registered
with the ``@terminal_retirer`` decorator (``serve.terminal_retirer``
sets ``__terminal_retirer__`` — the decorator IS the registration, so
the set of allowed callees is statically enumerable).

Two further shapes are findings anywhere:

* ``Completion(..., error="...")`` with no ``status=`` — the status
  defaults to ``"ok"`` while the error text says otherwise, a bug this
  pass caught for real in the paged engine's failed-admission paths;
* a *dynamic* ``status=<expr>`` outside the funnel — the analyzer can't
  prove it never takes a terminal value, so route it through a
  registered retirer instead.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .findings import Finding
from .index import FuncNode, Module, ModuleIndex, dotted, enclosing

CHECK = "terminal-funnel"

TERMINAL_STATUSES = frozenset(
    {"deadline_exceeded", "cancelled", "quarantined", "shed", "error"}
)

_FUNNEL_ROOT = "_early_retire"
_DECORATOR = "terminal_retirer"


def run(index: ModuleIndex) -> List[Finding]:
    findings: List[Finding] = []
    for mod in index.iter_modules():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if name is None or name.split(".")[-1] != "Completion":
                continue
            finding = _check_construction(mod, node)
            if finding is not None:
                findings.append(finding)
    return findings


def _enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    for anc in enclosing(node, FuncNode):
        return anc
    return None


def _is_registered(fn: Optional[ast.AST]) -> bool:
    """Inside _early_retire, or inside any @terminal_retirer function."""
    while fn is not None:
        if getattr(fn, "name", None) == _FUNNEL_ROOT:
            return True
        for dec in getattr(fn, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = dotted(target)
            if name is not None and name.split(".")[-1] == _DECORATOR:
                return True
        fn = _enclosing_function(fn)
    return False


def _check_construction(mod: Module, call: ast.Call) -> Optional[Finding]:
    status_kw: Optional[ast.keyword] = None
    error_kw: Optional[ast.keyword] = None
    for kw in call.keywords:
        if kw.arg == "status":
            status_kw = kw
        elif kw.arg == "error":
            error_kw = kw

    fn = _enclosing_function(call)
    symbol = mod.symbol_for(call)
    registered = _is_registered(fn)

    def finding(msg: str) -> Finding:
        return Finding(path=mod.path, line=call.lineno, check=CHECK, symbol=symbol, message=msg)

    if status_kw is None:
        if error_kw is not None and not (
            isinstance(error_kw.value, ast.Constant) and error_kw.value.value == ""
        ):
            return finding(
                "Completion carries error text but no status= — it defaults to "
                "'ok'; route through serve._early_retire or a @terminal_retirer"
            )
        return None

    value = status_kw.value
    if isinstance(value, ast.Constant) and isinstance(value.value, str):
        if value.value in TERMINAL_STATUSES and not registered:
            return finding(
                f"terminal Completion(status={value.value!r}) constructed outside "
                "the retirement funnel (serve._early_retire / @terminal_retirer)"
            )
        return None

    if not registered:
        return finding(
            "Completion with dynamic status= outside the retirement funnel — "
            "the analyzer cannot prove it is never terminal; use a @terminal_retirer"
        )
    return None
