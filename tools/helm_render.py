"""First-party `helm template` substitute for the in-repo chart.

The dev image carries no helm binary, so chart template OUTPUT was only
exercised on a real cluster (ARCHITECTURE.md known gap).  This module
implements the Go text/template + sprig subset the chart actually uses —
pipelines, define/include, if/else/range/with, variables, whitespace trim
markers, and the ~25 functions referenced by `templates/*.yaml` — enough to
render the chart hermetically and parse every emitted document as YAML in
tests (the render-test slot of the reference's CI; the reference relies on
`helm install` on a live kind cluster instead, demo/clusters/kind/scripts/
install-dra-driver.sh).

Not a general helm reimplementation: unsupported constructs raise
``RenderError`` loudly rather than misrender silently.

CLI: ``python -m tools.helm_render CHARTDIR [--set k=v ...]
[--release NAME] [--namespace NS]`` prints the multi-document YAML stream,
mirroring ``helm template``.
"""

from __future__ import annotations

import argparse
import dataclasses
import pathlib
import re
import sys
from typing import Any, Callable

import yaml


class RenderError(Exception):
    """Template could not be rendered (parse error or unsupported form)."""


class ChartFail(RenderError):
    """The template called ``fail`` — mirrors helm's render-time abort."""


# ---------------------------------------------------------------------------
# Lexing: split a template into literal text and {{ action }} nodes.

_ACTION_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


@dataclasses.dataclass
class _Action:
    src: str          # the action body, stripped
    trim_before: bool  # {{- : strip whitespace left of the action
    trim_after: bool   # -}} : strip whitespace right of the action


def _lex(template: str) -> list[Any]:
    """Return interleaved text strings and _Action nodes, trims applied."""
    nodes: list[Any] = []
    pos = 0
    for m in _ACTION_RE.finditer(template):
        text = template[m.start() : m.end()]
        before = template[pos : m.start()]
        act = _Action(
            src=m.group(1),
            trim_before=text.startswith("{{-"),
            trim_after=text.endswith("-}}"),
        )
        nodes.append(before)
        nodes.append(act)
        pos = m.end()
    nodes.append(template[pos:])
    # apply whitespace trim markers to neighbouring text nodes
    for i, node in enumerate(nodes):
        if not isinstance(node, _Action):
            continue
        if node.trim_before and i > 0:
            nodes[i - 1] = nodes[i - 1].rstrip(" \t\n\r")
        if node.trim_after and i + 1 < len(nodes):
            nodes[i + 1] = nodes[i + 1].lstrip(" \t\n\r")
    return nodes


# ---------------------------------------------------------------------------
# Expression (pipeline) parsing.  Grammar, per Go text/template:
#   pipeline := command ('|' command)*
#   command  := operand operand*
#   operand  := literal | '.' field-path | '$var' field-path? | '(' pipeline ')'
# A piped value is appended as the FINAL argument of the next command.

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<pipe>\|)
  | (?P<lparen>\() | (?P<rparen>\))
  | (?P<string>"(?:\\.|[^"\\])*")
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<dotpath>\.[A-Za-z_][\w.]*|\.)
  | (?P<var>\$[A-Za-z_]\w*|\$)
  | (?P<ident>[A-Za-z_][\w]*)
""",
    re.VERBOSE,
)

_GO_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == "\\" and i + 1 < len(body):
            out.append(_GO_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _tokenize_expr(src: str) -> list[tuple[str, str]]:
    toks = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise RenderError(f"cannot tokenize expression at: {src[pos:]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind != "ws":
            toks.append((kind, m.group()))
    return toks


# Parsed operand forms
@dataclasses.dataclass
class _Lit:
    value: Any


@dataclasses.dataclass
class _Dot:
    path: list[str]  # [] means bare '.'


@dataclasses.dataclass
class _Var:
    name: str        # '$' means the root variable
    path: list[str]


@dataclasses.dataclass
class _Call:
    name: str
    args: list[Any]


@dataclasses.dataclass
class _Paren:
    pipeline: "_Pipeline"


@dataclasses.dataclass
class _Pipeline:
    commands: list[Any]  # each command: _Lit | _Dot | _Var | _Call | _Paren


class _ExprParser:
    def __init__(self, toks: list[tuple[str, str]]):
        self.toks = toks
        self.pos = 0

    def peek(self):
        return self.toks[self.pos] if self.pos < len(self.toks) else (None, None)

    def next(self):
        tok = self.peek()
        self.pos += 1
        return tok

    def parse_pipeline(self) -> _Pipeline:
        commands = [self.parse_command()]
        while self.peek()[0] == "pipe":
            self.next()
            commands.append(self.parse_command())
        return _Pipeline(commands)

    def parse_command(self):
        operands = []
        while True:
            kind, _tok = self.peek()
            if kind in (None, "pipe", "rparen"):
                break
            operands.append(self.parse_operand())
        if not operands:
            raise RenderError("empty command in pipeline")
        head, rest = operands[0], operands[1:]
        if isinstance(head, _Call) or rest:
            # `f a b` — head must be a function name
            if not isinstance(head, _Call):
                raise RenderError(f"cannot apply arguments to {head}")
            head.args.extend(rest)
            return head
        return head

    def parse_operand(self):
        kind, tok = self.next()
        if kind == "string":
            return _Lit(_unquote(tok))
        if kind == "number":
            return _Lit(float(tok) if "." in tok else int(tok))
        if kind == "dotpath":
            path = [] if tok == "." else tok[1:].split(".")
            return _Dot(path)
        if kind == "var":
            return _Var(tok, [])
        if kind == "ident":
            if tok in ("true", "false"):
                return _Lit(tok == "true")
            if tok == "nil":
                return _Lit(None)
            return _Call(tok, [])
        if kind == "lparen":
            inner = self.parse_pipeline()
            k, _ = self.next()
            if k != "rparen":
                raise RenderError("unbalanced parenthesis in expression")
            return _Paren(inner)
        raise RenderError(f"unexpected token {tok!r}")


def _parse_expr(src: str) -> _Pipeline:
    parser = _ExprParser(_tokenize_expr(src))
    pipeline = parser.parse_pipeline()
    if parser.peek()[0] is not None:
        raise RenderError(f"trailing tokens in expression: {src!r}")
    return pipeline


# ---------------------------------------------------------------------------
# Structural parsing: nest if/range/with/define blocks.

@dataclasses.dataclass
class _Text:
    value: str


@dataclasses.dataclass
class _Output:
    pipeline: _Pipeline


@dataclasses.dataclass
class _Assign:
    var: str
    pipeline: _Pipeline


@dataclasses.dataclass
class _Cond:
    # list of (pipeline-or-None, body); None pipeline = else branch
    branches: list[tuple[Any, list]]


@dataclasses.dataclass
class _Range:
    var: str | None
    pipeline: _Pipeline
    body: list


@dataclasses.dataclass
class _With:
    pipeline: _Pipeline
    body: list


@dataclasses.dataclass
class _Define:
    name: str
    body: list


def _parse_nodes(nodes: list[Any]) -> list:
    """Parse the lexed node stream into a tree; returns top-level body."""
    pos = 0

    def parse_block(stop_on: tuple[str, ...]) -> tuple[list, str, _Action | None]:
        nonlocal pos
        body: list = []
        while pos < len(nodes):
            node = nodes[pos]
            pos += 1
            if isinstance(node, str):
                if node:
                    body.append(_Text(node))
                continue
            src = node.src
            if src.startswith("/*"):
                continue  # comment
            word = src.split(None, 1)[0] if src else ""
            if word in stop_on or (word == "else" and "else" in stop_on):
                return body, src, node
            if word == "if":
                body.append(parse_if(src[2:].strip()))
            elif word == "range":
                body.append(parse_range(src[5:].strip()))
            elif word == "with":
                inner, term, _ = parse_block_after()
                if not term == "end":
                    raise RenderError(f"'with' terminated by {term!r}, want 'end'")
                body.append(_With(_parse_expr(src[4:].strip()), inner))
            elif word == "define":
                m = re.match(r'define\s+"([^"]+)"$', src)
                if not m:
                    raise RenderError(f"malformed define: {src!r}")
                inner, term, _ = parse_block_after()
                if term != "end":
                    raise RenderError("'define' not closed with 'end'")
                body.append(_Define(m.group(1), inner))
            elif word == "end":
                raise RenderError("unexpected 'end'")
            elif re.match(r"^\$[A-Za-z_]\w*\s*:?=", src):
                var, _, rhs = src.partition("=")
                var = var.rstrip(": \t")
                body.append(_Assign(var, _parse_expr(rhs.strip())))
            else:
                body.append(_Output(_parse_expr(src)))
        return body, "", None

    def parse_block_after():
        return parse_block(("end", "else"))

    def parse_if(cond_src: str) -> _Cond:
        branches: list[tuple[Any, list]] = []
        cond: Any = _parse_expr(cond_src)
        while True:
            inner, term, _node = parse_block_after()
            branches.append((cond, inner))
            if term == "end":
                return _Cond(branches)
            if term == "else":
                final, term2, _ = parse_block_after()
                if term2 != "end":
                    raise RenderError("'else' block not closed with 'end'")
                branches.append((None, final))
                return _Cond(branches)
            if term.startswith("else if"):
                cond = _parse_expr(term[len("else if") :].strip())
                continue
            raise RenderError(f"'if' terminated by {term!r}")

    def parse_range(src: str) -> _Range:
        var = None
        m = re.match(r"^(\$[A-Za-z_]\w*)\s*:?=\s*(.*)$", src)
        if m:
            var, src = m.group(1), m.group(2)
        inner, term, _ = parse_block_after()
        if term != "end":
            raise RenderError("'range' not closed with 'end'")
        return _Range(var, _parse_expr(src), inner)

    body, term, _ = parse_block(())
    if term:
        raise RenderError(f"stray block terminator {term!r} at top level")
    return body


# ---------------------------------------------------------------------------
# Evaluation.


def _truthy(v: Any) -> bool:
    """Go template truthiness: zero values are false."""
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return v != 0
    if isinstance(v, (str, list, dict, tuple)):
        return len(v) > 0
    return True


def _go_str(v: Any) -> str:
    """%v-style formatting (lists render Go-like: [a b c])."""
    if v is None:
        return "<nil>"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    if isinstance(v, (list, tuple)):
        return "[" + " ".join(_go_str(x) for x in v) + "]"
    if isinstance(v, dict):
        return "map[" + " ".join(f"{k}:{_go_str(x)}" for k, x in sorted(v.items())) + "]"
    return str(v)


def _go_printf(fmt: str, args: list[Any]) -> str:
    out = []
    ai = 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        spec = fmt[i + 1] if i + 1 < len(fmt) else ""
        if spec == "%":
            out.append("%")
        else:
            if ai >= len(args):
                raise RenderError(f"printf: missing argument for %{spec}")
            arg = args[ai]
            ai += 1
            if spec == "q":
                out.append('"' + _go_str(arg).replace("\\", "\\\\").replace('"', '\\"') + '"')
            elif spec == "d":
                out.append(str(int(arg)))
            elif spec in ("v", "s"):
                out.append(_go_str(arg))
            else:
                raise RenderError(f"printf: unsupported verb %{spec}")
        i += 2
    return "".join(out)


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


def _indent(n: int, s: str) -> str:
    pad = " " * int(n)
    return "\n".join(pad + line if line else line for line in s.split("\n"))


def _kind_of(v: Any) -> str:
    if isinstance(v, bool):
        return "bool"
    if isinstance(v, int):
        return "int64"
    if isinstance(v, float):
        return "float64"
    if isinstance(v, str):
        return "string"
    if isinstance(v, (list, tuple)):
        return "slice"
    if isinstance(v, dict):
        return "map"
    if v is None:
        return "invalid"
    return type(v).__name__


def _num(v: Any) -> Any:
    """Coerce for numeric comparison the way sprig's untyped compares do."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        try:
            return float(v) if "." in v else int(v)
        except ValueError:
            return v
    return v


class _Renderer:
    def __init__(self, defines: dict[str, list], root: dict):
        self.defines = defines
        self.root = root  # the '.' for top-level templates

        self.funcs: dict[str, Callable[..., Any]] = {
            "default": lambda d, v=None: v if _truthy(v) else d,
            "quote": lambda *a: " ".join('"' + _go_str(x).replace("\\", "\\\\").replace('"', '\\"') + '"' for x in a),
            "trunc": lambda n, s: s[: int(n)] if int(n) >= 0 else s[int(n) :],
            "trimSuffix": lambda suf, s: s[: -len(suf)] if suf and s.endswith(suf) else s,
            "trimPrefix": lambda pre, s: s[len(pre) :] if pre and s.startswith(pre) else s,
            "upper": lambda s: s.upper(),
            "lower": lambda s: s.lower(),
            "indent": _indent,
            "nindent": lambda n, s: "\n" + _indent(n, s),
            "toYaml": _to_yaml,
            "int": lambda v: int(float(v)) if _truthy(v) or v == "0" or v == 0 else 0,
            "len": lambda v: len(v),
            "not": lambda v: not _truthy(v),
            "and": self._f_and,
            "or": self._f_or,
            "eq": lambda a, *rest: any(a == r for r in rest),
            "ne": lambda a, b: a != b,
            "lt": lambda a, b: _num(a) < _num(b),
            "le": lambda a, b: _num(a) <= _num(b),
            "gt": lambda a, b: _num(a) > _num(b),
            "ge": lambda a, b: _num(a) >= _num(b),
            "list": lambda *a: list(a),
            "dict": self._f_dict,
            "has": lambda needle, coll: needle in (coll or []),
            "hasKey": lambda d, k: isinstance(d, dict) and k in d,
            "kindIs": lambda kind, v: _kind_of(v) == kind,
            "printf": lambda fmt, *a: _go_printf(fmt, list(a)),
            "print": lambda *a: "".join(_go_str(x) for x in a),
            "fail": self._f_fail,
            "required": self._f_required,
            "join": lambda sep, coll: sep.join(_go_str(x) for x in coll or []),
            "split": lambda sep, s: dict((f"_{i}", part) for i, part in enumerate(s.split(sep))),
            "hasPrefix": lambda pre, s: isinstance(s, str) and s.startswith(pre),
            "hasSuffix": lambda suf, s: isinstance(s, str) and s.endswith(suf),
            "contains": lambda sub, s: isinstance(s, str) and sub in s,
            "regexMatch": lambda pat, s: re.search(pat, s or "") is not None,
            "replace": lambda old, new, s: s.replace(old, new),
            "empty": lambda v: not _truthy(v),
            "coalesce": lambda *a: next((x for x in a if _truthy(x)), None),
            "ternary": lambda t, f, cond: t if _truthy(cond) else f,
            "include": self._f_include,
            "tpl": self._f_tpl,
            "toString": _go_str,
            "trim": lambda s: s.strip(),
            "add": lambda *a: sum(_num(x) for x in a),
            "sub": lambda a, b: _num(a) - _num(b),
            "keys": lambda d: sorted(d.keys()),
            "first": lambda coll: coll[0] if coll else None,
            "last": lambda coll: coll[-1] if coll else None,
        }

    # -- function helpers needing renderer state
    def _f_and(self, *args):
        result: Any = True
        for a in args:
            result = a
            if not _truthy(a):
                return a
        return result

    def _f_or(self, *args):
        for a in args:
            if _truthy(a):
                return a
        return args[-1] if args else None

    def _f_dict(self, *kv):
        if len(kv) % 2:
            raise RenderError("dict: odd number of arguments")
        return {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}

    def _f_fail(self, msg):
        raise ChartFail(_go_str(msg))

    def _f_required(self, msg, v=None):
        if not _truthy(v):
            raise ChartFail(_go_str(msg))
        return v

    def _f_include(self, name, dot):
        body = self.defines.get(name)
        if body is None:
            raise RenderError(f"include of undefined template {name!r}")
        return self.render_body(body, dot, {"$": self.root})

    def _f_tpl(self, text, dot):
        nodes = _lex(text)
        body = _parse_nodes(nodes)
        return self.render_body(body, dot, {"$": self.root})

    # -- expression evaluation
    def eval_pipeline(self, p: _Pipeline, dot: Any, vars: dict) -> Any:
        value: Any = None
        have_value = False
        for cmd in p.commands:
            if have_value:
                if isinstance(cmd, _Call):
                    value = self.eval_call(cmd, dot, vars, piped=value)
                else:
                    raise RenderError("piped into a non-function operand")
            else:
                value = self.eval_operand(cmd, dot, vars)
                have_value = True
        return value

    def eval_operand(self, op: Any, dot: Any, vars: dict) -> Any:
        if isinstance(op, _Lit):
            return op.value
        if isinstance(op, _Paren):
            return self.eval_pipeline(op.pipeline, dot, vars)
        if isinstance(op, _Dot):
            return self._walk(dot, op.path)
        if isinstance(op, _Var):
            if op.name == "$":
                base = vars.get("$", self.root)
            elif op.name in vars:
                base = vars[op.name]
            else:
                raise RenderError(f"undefined variable {op.name}")
            return self._walk(base, op.path)
        if isinstance(op, _Call):
            return self.eval_call(op, dot, vars)
        raise RenderError(f"cannot evaluate operand {op!r}")

    def eval_call(self, call: _Call, dot: Any, vars: dict, piped: Any = ...) -> Any:
        fn = self.funcs.get(call.name)
        if fn is None:
            raise RenderError(f"unknown function {call.name!r}")
        args = [self.eval_operand(a, dot, vars) for a in call.args]
        if piped is not ...:
            args.append(piped)
        return fn(*args)

    @staticmethod
    def _walk(base: Any, path: list[str]) -> Any:
        cur = base
        for field in path:
            if isinstance(cur, dict):
                cur = cur.get(field)
            elif cur is None:
                return None
            else:
                raise RenderError(f"cannot access field {field!r} of {type(cur).__name__}")
        return cur

    # -- node rendering
    def render_body(self, body: list, dot: Any, vars: dict) -> str:
        out: list[str] = []
        # each body shares one variable scope (Go scopes per block; the
        # chart only ever assigns at file top level, so flat is faithful)
        for node in body:
            if isinstance(node, _Text):
                out.append(node.value)
            elif isinstance(node, _Output):
                v = self.eval_pipeline(node.pipeline, dot, vars)
                if v is not None:
                    out.append(v if isinstance(v, str) else _go_str(v))
            elif isinstance(node, _Assign):
                vars[node.var] = self.eval_pipeline(node.pipeline, dot, vars)
            elif isinstance(node, _Cond):
                for cond, branch in node.branches:
                    if cond is None or _truthy(self.eval_pipeline(cond, dot, vars)):
                        out.append(self.render_body(branch, dot, dict(vars)))
                        break
            elif isinstance(node, _Range):
                coll = self.eval_pipeline(node.pipeline, dot, vars)
                items = coll.items() if isinstance(coll, dict) else enumerate(coll or [])
                for _k, item in items:
                    inner_vars = dict(vars)
                    if node.var:
                        inner_vars[node.var] = item
                    out.append(self.render_body(node.body, item, inner_vars))
            elif isinstance(node, _With):
                v = self.eval_pipeline(node.pipeline, dot, vars)
                if _truthy(v):
                    out.append(self.render_body(node.body, v, dict(vars)))
            elif isinstance(node, _Define):
                pass  # collected in a pre-pass
            else:
                raise RenderError(f"cannot render node {node!r}")
        return "".join(out)


# ---------------------------------------------------------------------------
# Chart-level driver.


def _deep_merge(base: dict, override: dict) -> dict:
    out = dict(base)
    for k, v in override.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _collect_defines(body: list, into: dict[str, list]) -> None:
    for node in body:
        if isinstance(node, _Define):
            into[node.name] = node.body


def render_chart(
    chart_dir: str | pathlib.Path,
    values_override: dict | None = None,
    release_name: str = "tpu-dra-driver",
    namespace: str = "tpu-dra-driver",
) -> dict[str, str]:
    """Render every template; returns {template-filename: rendered-text}.

    Raises ChartFail when a template calls ``fail`` (the validation path)
    and RenderError on malformed/unsupported templates.
    """
    chart_dir = pathlib.Path(chart_dir)
    chart_meta = yaml.safe_load((chart_dir / "Chart.yaml").read_text())
    values = yaml.safe_load((chart_dir / "values.yaml").read_text()) or {}
    if values_override:
        values = _deep_merge(values, values_override)

    root = {
        "Values": values,
        "Chart": {
            "Name": chart_meta.get("name", chart_dir.name),
            "Version": chart_meta.get("version", "0.0.0"),
            "AppVersion": str(chart_meta.get("appVersion", "0.0.0")),
        },
        "Release": {
            "Name": release_name,
            "Namespace": namespace,
            "Service": "Helm",
            "IsInstall": True,
            "IsUpgrade": False,
        },
        "Capabilities": {"KubeVersion": {"Version": "v1.32.0", "Major": "1", "Minor": "32"}},
    }

    template_dir = chart_dir / "templates"
    parsed: dict[str, list] = {}
    defines: dict[str, list] = {}
    for path in sorted(template_dir.iterdir()):
        if path.suffix not in (".yaml", ".tpl") or path.name.startswith("."):
            continue
        body = _parse_nodes(_lex(path.read_text()))
        parsed[path.name] = body
        _collect_defines(body, defines)

    renderer = _Renderer(defines, root)
    rendered: dict[str, str] = {}
    for name, body in parsed.items():
        if name.endswith(".tpl"):
            continue  # helpers: defines only
        rendered[name] = renderer.render_body(body, root, {"$": root})
    return rendered


def render_chart_docs(
    chart_dir: str | pathlib.Path, **kwargs: Any
) -> list[dict]:
    """Render and YAML-parse; returns the non-empty documents (helm's
    post-render object stream)."""
    docs: list[dict] = []
    for name, text in render_chart(chart_dir, **kwargs).items():
        try:
            for doc in yaml.safe_load_all(text):
                if doc is not None:
                    if not isinstance(doc, dict):
                        raise RenderError(f"{name}: rendered a non-mapping document: {doc!r}")
                    docs.append(doc)
        except yaml.YAMLError as exc:
            raise RenderError(f"{name}: rendered invalid YAML: {exc}") from exc
    return docs


def _parse_set(pairs: list[str]) -> dict:
    """--set a.b=c overrides (string/bool/int literal inference)."""
    out: dict = {}
    for pair in pairs:
        key, _, raw = pair.partition("=")
        value: Any = raw
        if raw in ("true", "false"):
            value = raw == "true"
        elif re.fullmatch(r"-?\d+", raw):
            value = int(raw)
        elif raw.startswith("[") or raw.startswith("{"):
            value = yaml.safe_load(raw)
        cur = out
        parts = key.split(".")
        for p in parts[:-1]:
            cur = cur.setdefault(p, {})
        cur[parts[-1]] = value
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description="render the helm chart hermetically")
    ap.add_argument("chart_dir")
    ap.add_argument("--set", action="append", default=[], dest="sets", metavar="K=V")
    ap.add_argument("--release", default="tpu-dra-driver")
    ap.add_argument("--namespace", default="tpu-dra-driver")
    args = ap.parse_args(argv)
    try:
        rendered = render_chart(
            args.chart_dir,
            values_override=_parse_set(args.sets),
            release_name=args.release,
            namespace=args.namespace,
        )
    except ChartFail as exc:
        print(f"Error: execution error: {exc}", file=sys.stderr)
        return 1
    for name, text in rendered.items():
        if not text.strip():
            continue
        print(f"---\n# Source: templates/{name}")
        print(text.strip("\n"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
