#!/usr/bin/env python
"""One-shot diagnostics snapshot of a LIVE driver/controller process —
the ``nvidia-bug-report.sh`` analogue for this driver.

Pulls every diagnostics endpoint of a running DiagnosticsServer
(utils/diagnostics.py) over HTTP and writes them into a single JSON
bundle, shaped like the in-process bundles utils/watchdog.py dumps on a
stall — one artifact to attach to a bug report either way:

  /healthz        liveness
  /metrics        Prometheus text exposition
  /debug/state    the owner's state snapshot
  /debug/traces   the tracer ring's recent spans
  /debug/journal  the claim-lifecycle flight recorder's tail
  /debug/stacks   every Python thread's stack
  /debug/serve    per-engine EngineStats + recent request traces (the
                  serving load-signal contract; empty when the process
                  hosts no serving engine)

``--fleet`` widens the snapshot to the observability plane: the target
is a CONTROL PLANE whose DiagnosticsServer federates its supervised
workers' telemetry (models/obs_plane.py), so one bundle captures every
worker's journal tail, spans and metrics — the metrics section already
carries each worker's registry under its ``instance=`` label, and two
more sections land alongside it:

  /debug/fleet-journal  merged, instance-tagged journal across workers
  /debug/fleet-traces   merged, skew-normalized span trees

Per-endpoint failures are recorded in the bundle as ``"error: ..."``
strings rather than aborting: a half-wedged process is EXACTLY the one
worth snapshotting, and whatever still answers must land in the bundle.

Usage:
    python tools/diag_bundle.py --url http://127.0.0.1:8080 [--out DIR]
    python tools/diag_bundle.py --port 8080   # shorthand for localhost
    python tools/diag_bundle.py --port 8080 --fleet   # + fleet sections

Prints the bundle path on success; exits 1 when NO endpoint answered
(nothing listening is the one case with nothing to bundle).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

ENDPOINTS = {
    "healthz": "/healthz",
    "metrics": "/metrics",
    "state": "/debug/state",
    "traces": "/debug/traces",
    "journal": "/debug/journal?limit=500",
    "thread_stacks": "/debug/stacks",
    "serve": "/debug/serve?limit=16",
}

FLEET_ENDPOINTS = {
    "fleet_journal": "/debug/fleet-journal?limit=500",
    "fleet_traces": "/debug/fleet-traces?limit=100",
}

TEXT_SECTIONS = {"healthz", "metrics"}  # not JSON on the wire


def fetch(url: str, timeout_s: float):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode()


def collect(base_url: str, timeout_s: float = 5.0,
            fleet: bool = False) -> tuple[dict, int]:
    """Pull every endpoint; returns (sections, n_answered)."""
    sections: dict = {}
    answered = 0
    endpoints = {**ENDPOINTS, **(FLEET_ENDPOINTS if fleet else {})}
    for name, path in endpoints.items():
        try:
            body = fetch(base_url.rstrip("/") + path, timeout_s)
            sections[name] = body if name in TEXT_SECTIONS else json.loads(body)
            answered += 1
        except (urllib.error.URLError, OSError, ValueError) as exc:
            sections[name] = f"error: {type(exc).__name__}: {exc}"
    return sections, answered


def build_bundle(base_url: str, timeout_s: float = 5.0,
                 fleet: bool = False) -> tuple[dict, int]:
    sections, answered = collect(base_url, timeout_s, fleet=fleet)
    bundle = {
        "kind": "tpu-dra-fleet-diag-bundle" if fleet else "tpu-dra-diag-bundle",
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "reason": f"diag_bundle.py snapshot of {base_url}",
        "source": base_url,
        **sections,
    }
    return bundle, answered


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="diag_bundle", description=__doc__)
    parser.add_argument("--url", default="", help="base URL of the diagnostics server")
    parser.add_argument(
        "--port", type=int, default=0,
        help="shorthand: snapshot http://127.0.0.1:PORT",
    )
    parser.add_argument(
        "--out", default="",
        help="output directory (default: $TPU_DRA_DIAG_DIR or $TMPDIR/tpu-dra-diag)",
    )
    parser.add_argument("--timeout-s", type=float, default=5.0)
    parser.add_argument(
        "--fleet", action="store_true",
        help="also pull the observability plane's federated sections "
             "(/debug/fleet-journal, /debug/fleet-traces); the metrics "
             "section then carries every worker under instance= labels",
    )
    args = parser.parse_args(argv)
    if bool(args.url) == bool(args.port):
        parser.error("exactly one of --url or --port is required")
    base_url = args.url or f"http://127.0.0.1:{args.port}"

    bundle, answered = build_bundle(base_url, args.timeout_s, fleet=args.fleet)
    if answered == 0:
        print(f"diag_bundle: nothing listening at {base_url}", file=sys.stderr)
        return 1

    out_dir = Path(
        args.out
        or os.environ.get("TPU_DRA_DIAG_DIR", "")
        or Path(os.environ.get("TMPDIR", "/tmp")) / "tpu-dra-diag"
    )
    out_dir.mkdir(parents=True, exist_ok=True)
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    kind = "fleet" if args.fleet else "remote"
    out = out_dir / f"diag-bundle-{stamp}-{kind}.json"
    out.write_text(json.dumps(bundle, indent=1, default=str))
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
