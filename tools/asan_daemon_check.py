"""Drive the ASAN/UBSAN build of the native topology daemon through one
full protocol round trip (info/register/acquire/contend/release), so
memory errors or UB in the request path fail `make asan-test` loudly.

The sanitized binary aborts on any finding; a clean exit after real
socket traffic is the pass signal.
"""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import tempfile
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
BINARY = REPO / "k8s_dra_driver_tpu/tpuinfo/cpp/tpu_topology_daemon_asan"

sys.path.insert(0, str(REPO))

from k8s_dra_driver_tpu.plugin.topology_daemon import (  # noqa: E402
    TopologyDaemonClient,
    claim_socket_path,
)

PARTITIONS = [
    {"index": 0, "visible_devices": "0", "hbm_limit_mib": 4096},
    {"index": 1, "visible_devices": "1", "hbm_limit_mib": None},
]


def main() -> int:
    with tempfile.TemporaryDirectory(prefix="asan-daemon-") as tmp:
        proc = subprocess.Popen(
            [str(BINARY), "--claim-uid", "asan", "--socket-dir", tmp],
            env={
                "PATH": "/usr/bin:/bin",
                "TPU_PARTITIONS": json.dumps(PARTITIONS),
                "TPU_PARTITION_SPEC": "2,1,1",
                "TPU_HBM_LIMITS": "u0=4096Mi",
                "TPU_QUEUE_QUANTUM_MS": "10",
            },
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        try:
            sock = claim_socket_path(tmp, "asan")
            deadline = time.time() + 10
            while time.time() < deadline and not pathlib.Path(sock).exists():
                if proc.poll() is not None:
                    print(proc.stdout.read().decode(), file=sys.stderr)
                    return 1
                time.sleep(0.02)
            a = TopologyDaemonClient(sock, "a")
            b = TopologyDaemonClient(sock, "b")
            assert a.info()["ok"]
            assert a.register(partition=0)["ok"]
            assert not a.register(partition=9)["ok"]  # error path
            assert a.acquire(quantum_ms=10, scope="0")["ok"]
            assert not b.acquire(quantum_ms=10, scope="0", timeout_ms=30)["ok"]
            assert a.release(scope="0")["ok"]
            assert b.acquire(quantum_ms=10, scope="0", timeout_ms=500)["ok"]
            # malformed line must be answered, not crash the daemon
            import socket as socketlib

            s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
            s.connect(sock)
            s.sendall(b"{broken\n")
            assert not json.loads(s.makefile("rb").readline())["ok"]
            # interior-sign residue: strict grammar must error, not read 12
            s.sendall(b'{"op": "info", "x": 12-3}\n')
            assert not json.loads(s.makefile("rb").readline())["ok"]
            s.close()
            a.close(), b.close()
            # Shutdown with an in-flight blocked acquire — the round-2
            # advisor's use-after-free: a worker thread parked in acquire()'s
            # cond-wait while main destroys the Daemon.  run() now stop()s
            # the daemon and JOINS every worker, so this must exit clean.
            holder = TopologyDaemonClient(sock, "holder")
            assert holder.acquire(quantum_ms=60000, scope="z")["ok"]
            waiter = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
            waiter.connect(sock)
            waiter.sendall(
                json.dumps(
                    {"op": "acquire", "consumer": "w", "scope": "z",
                     "timeout_ms": 30000}
                ).encode() + b"\n"
            )
            time.sleep(0.3)  # let the worker park in cond_.wait_until
            # leave holder + waiter connections open across SIGTERM
        finally:
            proc.terminate()
            try:
                rc = proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                # shutdown-hang regression (the very bug this check guards):
                # reap the daemon and fail with the diagnostic, don't leak
                # it and die on an unhandled traceback
                proc.kill()
                proc.wait(timeout=10)
                rc = -9
        out = proc.stdout.read().decode()
        # The daemon handles SIGTERM by closing its listener and returning
        # from main NORMALLY, so LeakSanitizer's end-of-process report runs
        # — rc must be 0 and no sanitizer may have spoken.
        bad = ("ERROR: AddressSanitizer", "ERROR: LeakSanitizer", "runtime error")
        if rc != 0 or any(m in out for m in bad):
            print(f"rc={rc}\n{out}", file=sys.stderr)
            return 1
        print("asan daemon check: ok (clean exit, no sanitizer findings)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
