"""Fast accelerator-tunnel liveness probe.

``python tools/tunnel_probe.py [timeout_s]`` — exits 0 and prints the backend
name if a real matmul completes on the default jax backend within the timeout,
exits 1 otherwise.  Runs the probe in a subprocess because a dead axon tunnel
makes backend init HANG (not raise), and a hung in-process init can never be
retried.  ``bench._wait_for_backend`` imports :func:`probe` for the round-end
artifact — one implementation, two call sites.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys

_PROBE_CODE = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256), jnp.bfloat16);"
    "(x @ x).block_until_ready();"
    "print(jax.default_backend())"
)

# Detail of the most recent FAILED probe (timeout marker, or rc + stderr
# tail) — empty after a success.  Callers that cache the probe verdict
# (bench._wait_for_backend) attach this to their degraded-body marker so
# the artifact says WHY the backend was judged down.
LAST_ERROR = ""


def probe(timeout_s: float = 90.0, quiet: bool = False) -> bool:
    """One subprocess attempt to init the backend and run a real matmul.

    ``start_new_session`` + killpg on timeout: jax may spawn grandchildren
    holding the stdout pipe, and a child stuck in an uninterruptible
    device-driver call survives a plain ``kill()`` — either would turn
    ``subprocess.run``'s post-timeout ``communicate()`` into a second
    unbounded hang, exactly the failure this subprocess exists to bound.
    """
    global LAST_ERROR
    say = (lambda *a: None) if quiet else (lambda *a: print(*a))
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_CODE],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # D-state child: give up on reaping, report down
        LAST_ERROR = f"timeout after {timeout_s:.0f}s (backend init hung)"
        say("tunnel_probe: TIMEOUT (backend init hung)")
        return False
    if proc.returncode == 0:
        LAST_ERROR = ""
        say(f"tunnel_probe: OK backend={out.strip().splitlines()[-1]}")
        return True
    tail = (err or "").strip().splitlines()
    LAST_ERROR = f"rc={proc.returncode}: {tail[-1] if tail else ''}".strip()
    say(f"tunnel_probe: DOWN rc={proc.returncode} {tail[-1] if tail else ''}")
    return False


if __name__ == "__main__":
    t = float(sys.argv[1]) if len(sys.argv) > 1 else 90.0
    sys.exit(0 if probe(t) else 1)
