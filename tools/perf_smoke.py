"""Hot-path perf budget guard (<30s, runs in tier-1 via tests/test_perf_smoke.py).

Runs 200 allocate/prepare/unprepare/deallocate cycles plus a batched-prepare
phase in-process against a fake v5e-8 host, then fails if the exported
counters show the hot path regressed to per-call recomputation:

* ``dra_cel_evals_total`` — with the allocation index + per-candidate
  verdict memo, selector CEL evaluates once per device per inventory
  version; 200 cycles against UNCHANGED inventory must stay near the
  one-time warmup cost (O(changed pools)), nowhere near
  O(cycles x devices x selectors) (~thousands before PR 2).
* ``dra_alloc_index_misses_total`` — pool snapshots rebuild only when a
  pool's slices change; an unchanged cluster allows only the initial build.
* ``dra_checkpoint_writes_total`` — group commit pays ONE durable write per
  NodePrepareResources/NodeUnprepareResources call, regardless of how many
  claims the call carries.

``check_pipelined_decode`` guards the DATA-plane hot loop the same way: a
tiny-model burst engine (models/serve.py ``sync_interval`` > 1) must drain
a fixed workload inside ``PIPELINED_DECODE_BUDGET_S`` on CPU and within
the host-sync ceiling — one sync per token creeping back in busts the
budget long before it shows up on a real chip.

Exits non-zero (CLI) / raises PerfBudgetError (pytest wrapper) on any
busted budget, so a future PR cannot silently reintroduce the quadratic.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

CYCLES = 200
BATCH_ROUNDS = 5
BATCH_SIZE = 8

# One-time warmup evaluates each DeviceClass/request selector once per
# candidate (a v5e-8 host publishes a few dozen devices across chip /
# subslice / membership types); 400 is ~4x that warmup and ~10x below what
# a single cycle-coupled regression would produce over 200 cycles.
CEL_EVAL_CEILING = 400
# Initial snapshot builds each pool once; unchanged inventory allows no
# further rebuilds (small slack for claim-driven consumed-set rebuilds
# that a refactor might reclassify as pool rebuilds).
INDEX_MISS_CEILING = 4


class PerfBudgetError(AssertionError):
    pass


def check(cycles: int = CYCLES) -> dict:
    from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
    from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
    from k8s_dra_driver_tpu.utils.metrics import REGISTRY

    work = tempfile.mkdtemp(prefix="tpu-dra-perf-smoke-")
    cluster = make_cluster(hosts=1, topology="v5e-8", work_dir=work)
    node = "tpu-host-0"
    labels = cluster.node_labels(node)
    driver = Driver(
        cluster.server,
        DriverConfig(
            node_name=node,
            cdi_root=f"{work}/cdi",
            checkpoint_path=f"{work}/checkpoint.json",
            topology_env={"TPUINFO_FAKE_TOPOLOGY": "v5e-8", "TPUINFO_FAKE_HOST_ID": "0"},
            publish=False,
        ),
    )
    evals = REGISTRY.counter("dra_cel_evals_total")
    writes = REGISTRY.counter("dra_checkpoint_writes_total")
    misses = REGISTRY.counter("dra_alloc_index_misses_total")
    hits = REGISTRY.counter("dra_alloc_index_hits_total")
    evals0, writes0, misses0 = evals.value(), writes.value(), misses.value()

    start = time.perf_counter()
    for i in range(cycles):
        name = f"smoke-{i}"
        claim = cluster.server.create(simple_claim(name))
        allocated = cluster.allocator.allocate(claim, node_name=node, node_labels=labels)
        ref = ClaimRef(uid=allocated.metadata.uid, name=name, namespace="default")
        res = driver.node_prepare_resources([ref])[allocated.metadata.uid]
        if res.error:
            raise RuntimeError(f"prepare failed: {res.error}")
        driver.node_unprepare_resources([ref])
        cluster.allocator.deallocate(
            cluster.server.get("ResourceClaim", name, "default")
        )
        cluster.server.delete("ResourceClaim", name, "default")
    single_claim_writes = int(writes.value() - writes0)

    batch_writes0 = writes.value()
    for r in range(BATCH_ROUNDS):
        refs = []
        for k in range(BATCH_SIZE):
            name = f"smoke-batch-{r}-{k}"
            claim = cluster.server.create(simple_claim(name))
            allocated = cluster.allocator.allocate(
                claim, node_name=node, node_labels=labels
            )
            refs.append(
                ClaimRef(uid=allocated.metadata.uid, name=name, namespace="default")
            )
        out = driver.node_prepare_resources(refs)
        errors = [x.error for x in out.values() if x.error]
        if errors:
            raise RuntimeError(f"batched prepare failed: {errors}")
        driver.node_unprepare_resources(refs)
        for ref in refs:
            cluster.allocator.deallocate(
                cluster.server.get("ResourceClaim", ref.name, "default")
            )
            cluster.server.delete("ResourceClaim", ref.name, "default")
    elapsed = time.perf_counter() - start

    stats = {
        "cycles": cycles,
        "batch_rounds": BATCH_ROUNDS,
        "batch_size": BATCH_SIZE,
        "elapsed_s": round(elapsed, 2),
        "cel_evals": int(evals.value() - evals0),
        "cel_eval_ceiling": CEL_EVAL_CEILING,
        "index_misses": int(misses.value() - misses0),
        "index_miss_ceiling": INDEX_MISS_CEILING,
        "index_hits": int(hits.value()),
        "single_claim_checkpoint_writes": single_claim_writes,
        "batched_checkpoint_writes": int(writes.value() - batch_writes0),
        "batched_checkpoint_write_ceiling": 2 * BATCH_ROUNDS,
    }
    if stats["cel_evals"] > CEL_EVAL_CEILING:
        raise PerfBudgetError(
            f"CEL evals {stats['cel_evals']} > ceiling {CEL_EVAL_CEILING}: "
            f"selector evaluation is no longer memoized per inventory version"
        )
    if stats["index_misses"] > INDEX_MISS_CEILING:
        raise PerfBudgetError(
            f"index misses {stats['index_misses']} > ceiling {INDEX_MISS_CEILING}: "
            f"pool snapshots are being rebuilt without inventory changes"
        )
    # 2 durable writes per single-claim cycle (one per gRPC-call batch of 1)
    # is the contract; more means checkpoint writes crept onto a sub-step.
    if single_claim_writes > 2 * cycles:
        raise PerfBudgetError(
            f"single-claim checkpoint writes {single_claim_writes} > {2 * cycles}: "
            f"more than one durable write per prepare/unprepare call"
        )
    if stats["batched_checkpoint_writes"] > 2 * BATCH_ROUNDS:
        raise PerfBudgetError(
            f"batched checkpoint writes {stats['batched_checkpoint_writes']} > "
            f"{2 * BATCH_ROUNDS}: group commit is not batching "
            f"({BATCH_SIZE}-claim calls must cost one write each way)"
        )
    return stats


# Timed window: 8 requests x 16 tokens through a 4-slot burst engine,
# compiles excluded.  ~0.2s on an idle 1-core CPU runner; 1s absorbs
# shared-runner noise while still catching a per-token host sync (which
# multiplies the dispatch count by sync_interval) or a retrace per burst.
PIPELINED_DECODE_BUDGET_S = 1.0
# Ideal sync count for the workload is ~8 (two 4-slot waves x two bursts
# per 16-token stream, plus drain tails); 24 leaves 3x slack and sits 5x
# under the ~128 a one-sync-per-token regression would produce.
PIPELINED_SYNC_CEILING = 24


def check_pipelined_decode() -> dict:
    """Budget guard for the pipelined decode loop (PR 4 tentpole): the
    burst engine must stay compiled (no per-burst retrace) and must NOT
    sync the host per token.  CPU-deterministic: greedy sampling, fixed
    prompts, tiny model."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]
    eng = serve.ServeEngine(
        params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
    )
    eng.pump([(prompts[0], 16)])  # compile admission + burst off the clock
    eng.host_syncs = 0
    start = time.perf_counter()
    done = eng.pump([(p, 16) for p in prompts])
    elapsed = time.perf_counter() - start
    stats = {
        "requests": len(done),
        "generated_tokens": sum(len(c.generated) for c in done),
        "elapsed_s": round(elapsed, 3),
        "budget_s": PIPELINED_DECODE_BUDGET_S,
        "host_syncs": eng.host_syncs,
        "host_sync_ceiling": PIPELINED_SYNC_CEILING,
    }
    if len(done) != len(prompts):
        raise PerfBudgetError(
            f"pipelined pump drained {len(done)}/{len(prompts)} requests"
        )
    if elapsed > PIPELINED_DECODE_BUDGET_S:
        raise PerfBudgetError(
            f"pipelined decode took {elapsed:.2f}s > "
            f"{PIPELINED_DECODE_BUDGET_S}s budget: the burst loop is "
            f"retracing or syncing per token"
        )
    if eng.host_syncs > PIPELINED_SYNC_CEILING:
        raise PerfBudgetError(
            f"pipelined decode paid {eng.host_syncs} host syncs > ceiling "
            f"{PIPELINED_SYNC_CEILING}: per-token readback crept back into "
            f"the burst loop"
        )
    return stats


# Shedding is the overload escape hatch: it must stay a pure host-side
# queue operation.  The whole overloaded pump (serve 3 + shed 5) gets the
# same 1s window as the decode guard; the shed path itself adds only list
# pops and Completion construction to it.
SHED_FASTPATH_BUDGET_S = 1.0


def check_shed_fastpath() -> dict:
    """Budget guard for load shedding (PR 5 tentpole): rejecting overflow
    must cost ZERO device dispatches — an overloaded pump pays exactly the
    host syncs of a twin pumping only the admissible prefix, and the typed
    rejections land inside the time budget.  A shed path that touches the
    device (a stray block reservation, a prefill probe) turns the overload
    escape hatch into more overload."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=6)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=3, prompt_bucket=16, sync_interval=4
        )

    engine().pump([(prompts[0], 8)])  # compile off the clock (shared_jit)
    twin = engine()
    twin.pump([(p, 8) for p in prompts[:3]])

    shed_eng = engine()
    start = time.perf_counter()
    done = shed_eng.pump([(p, 8) for p in prompts], queue_limit=0)
    elapsed = time.perf_counter() - start
    sheds = [c for c in done if c.status == "shed"]
    served = [c for c in done if c.status == "ok"]
    stats = {
        "served": len(served),
        "sheds": len(sheds),
        "host_syncs": shed_eng.host_syncs,
        "twin_host_syncs": twin.host_syncs,
        "elapsed_s": round(elapsed, 3),
        "budget_s": SHED_FASTPATH_BUDGET_S,
    }
    if len(served) != 3 or len(sheds) != len(prompts) - 3:
        raise PerfBudgetError(
            f"shed fastpath served {len(served)} / shed {len(sheds)}, "
            f"expected 3 served + {len(prompts) - 3} shed"
        )
    if shed_eng.host_syncs != twin.host_syncs:
        raise PerfBudgetError(
            f"shedding paid device work: {shed_eng.host_syncs} host syncs "
            f"vs {twin.host_syncs} for the admissible prefix alone — "
            f"rejections must never dispatch"
        )
    if elapsed > SHED_FASTPATH_BUDGET_S:
        raise PerfBudgetError(
            f"overloaded pump took {elapsed:.2f}s > "
            f"{SHED_FASTPATH_BUDGET_S}s budget: shedding is no longer a "
            f"host-side fast path"
        )
    return stats


# Telemetry must ride existing sync points: the hard gate is EXACT host-
# sync equality against a telemetry-off twin (deterministic — any added
# readback shows up as a counter mismatch).  Wall clock is the soft gate:
# ≤5% relative overhead, with an absolute floor because at this workload's
# ~0.2s scale a shared CI runner's scheduling jitter alone exceeds 5%.
TELEMETRY_OVERHEAD_FRAC = 0.05
TELEMETRY_OVERHEAD_FLOOR_S = 0.05
TELEMETRY_REPS = 3


def check_telemetry_overhead() -> dict:
    """Budget guard for request-lifecycle telemetry (PR 6 tentpole): a
    pump with telemetry on pays EXACTLY its telemetry-off twin's host
    syncs (zero added device->host readbacks — timestamps only at burst
    boundaries the engine already synchronizes at) and at most ~5%
    wall-clock overhead for the host-side bookkeeping."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine(telemetry: bool):
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16,
            sync_interval=8, telemetry_enabled=telemetry,
        )

    engine(True).pump([(prompts[0], 16)])  # compile off the clock (shared_jit)

    def run(telemetry: bool):
        eng = engine(telemetry)
        start = time.perf_counter()
        done = eng.pump([(p, 16) for p in prompts])
        return time.perf_counter() - start, eng.host_syncs, len(done)

    # best-of-N, interleaved, so a one-off scheduler hiccup cannot land
    # entirely on one arm of the comparison
    off_wall, on_wall = [], []
    off_syncs = on_syncs = drained = 0
    for _ in range(TELEMETRY_REPS):
        w, off_syncs, drained = run(False)
        off_wall.append(w)
        w, on_syncs, drained = run(True)
        on_wall.append(w)
    base, tele = min(off_wall), min(on_wall)
    budget = base * (1 + TELEMETRY_OVERHEAD_FRAC) + TELEMETRY_OVERHEAD_FLOOR_S
    stats = {
        "requests": drained,
        "telemetry_off_s": round(base, 3),
        "telemetry_on_s": round(tele, 3),
        "overhead_frac": round(tele / base - 1, 4) if base > 0 else 0.0,
        "budget_frac": TELEMETRY_OVERHEAD_FRAC,
        "floor_s": TELEMETRY_OVERHEAD_FLOOR_S,
        "host_syncs_off": off_syncs,
        "host_syncs_on": on_syncs,
    }
    if on_syncs != off_syncs:
        raise PerfBudgetError(
            f"telemetry added host syncs: {on_syncs} with telemetry vs "
            f"{off_syncs} without — lifecycle timing must piggyback on "
            f"existing burst-boundary readbacks, never add its own"
        )
    if tele > budget:
        raise PerfBudgetError(
            f"telemetry overhead {tele:.3f}s > {budget:.3f}s "
            f"({base:.3f}s base + {TELEMETRY_OVERHEAD_FRAC:.0%} + "
            f"{TELEMETRY_OVERHEAD_FLOOR_S}s floor): per-request tracing is "
            f"no longer cheap dict bookkeeping"
        )
    return stats


# The router must be a pure host-side placement layer: a 1-replica fleet
# pays EXACTLY the bare engine's host syncs (zero added device
# dispatches — routing is dict/clock work over stats() snapshots), and
# its per-tick bookkeeping (health verdicts, scoring, journal) stays
# inside a small wall-clock envelope over the bare pump.
ROUTER_OVERHEAD_FRAC = 0.10
ROUTER_OVERHEAD_FLOOR_S = 0.10


def check_router_overhead() -> dict:
    """Budget guard for the fleet router (PR 7 tentpole): fronting ONE
    replica through FleetRouter.pump() must dispatch exactly the device
    work of the bare engine pumping the same requests, and the router's
    host-side work (health ticks, candidate scoring, fleet queue) must
    stay bounded."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, fleet, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
        )

    reqs = [{"prompt": p, "max_tokens": 16} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    bare = engine()
    start = time.perf_counter()
    done_bare = bare.pump([dict(r) for r in reqs])
    bare_wall = time.perf_counter() - start

    routed_eng = engine()
    router = fleet.FleetRouter([routed_eng])
    start = time.perf_counter()
    done_routed = router.pump([dict(r) for r in reqs])
    routed_wall = time.perf_counter() - start

    budget = bare_wall * (1 + ROUTER_OVERHEAD_FRAC) + ROUTER_OVERHEAD_FLOOR_S
    stats = {
        "requests_bare": len(done_bare),
        "requests_routed": len(done_routed),
        "host_syncs_bare": bare.host_syncs,
        "host_syncs_routed": routed_eng.host_syncs,
        "bare_s": round(bare_wall, 3),
        "routed_s": round(routed_wall, 3),
        "budget_frac": ROUTER_OVERHEAD_FRAC,
        "floor_s": ROUTER_OVERHEAD_FLOOR_S,
    }
    if len(done_routed) != len(reqs) or len(done_bare) != len(reqs):
        raise PerfBudgetError(
            f"router overhead run drained {len(done_routed)}/{len(reqs)} "
            f"routed vs {len(done_bare)} bare"
        )
    if routed_eng.host_syncs != bare.host_syncs:
        raise PerfBudgetError(
            f"fleet routing added device work: {routed_eng.host_syncs} host "
            f"syncs through the router vs {bare.host_syncs} bare — placement "
            f"must stay a host-side decision over stats() snapshots"
        )
    if routed_wall > budget:
        raise PerfBudgetError(
            f"routed pump took {routed_wall:.3f}s > {budget:.3f}s "
            f"({bare_wall:.3f}s bare + {ROUTER_OVERHEAD_FRAC:.0%} + "
            f"{ROUTER_OVERHEAD_FLOOR_S}s floor): per-tick router bookkeeping "
            f"is no longer cheap host work"
        )
    return stats


# Disaggregation's whole bet is that the handoff is cheap: a 1-prefill/
# 1-decode pair may pay AT MOST the unified engine's host syncs plus one
# KV capture per request (the single device->host readback that forms the
# transfer payload).  Anything above that means the handoff path grew
# per-token syncs — the overhead that erases the TTFT win.
DISAGG_OVERHEAD_FRAC = 0.50
DISAGG_OVERHEAD_FLOOR_S = 0.25


def check_handoff_overhead() -> dict:
    """Budget guard for the disaggregated handoff (PR 8 tentpole): a
    1-prefill/1-decode DisaggRouter pays no more host syncs per token
    than the unified engine PLUS exactly one transfer (= one KV capture
    sync) per request, and the host-side channel/router bookkeeping stays
    inside a wall-clock envelope over the unified pump."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, disagg, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
        )

    reqs = [{"prompt": p, "max_tokens": 16} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    unified = engine()
    start = time.perf_counter()
    done_unified = unified.pump([dict(r) for r in reqs])
    unified_wall = time.perf_counter() - start

    pre, dec = engine(), engine()
    router = disagg.DisaggRouter(prefill=[pre], decode=[dec])
    start = time.perf_counter()
    done_disagg = router.pump([dict(r) for r in reqs])
    disagg_wall = time.perf_counter() - start

    disagg_syncs = pre.host_syncs + dec.host_syncs
    sync_ceiling = unified.host_syncs + len(reqs)
    budget = unified_wall * (1 + DISAGG_OVERHEAD_FRAC) + DISAGG_OVERHEAD_FLOOR_S
    stats = {
        "requests_unified": len(done_unified),
        "requests_disagg": len(done_disagg),
        "host_syncs_unified": unified.host_syncs,
        "host_syncs_disagg": disagg_syncs,
        "host_sync_ceiling": sync_ceiling,
        "transfers_ok": router.channel.counts.get(disagg.OK, 0),
        "unified_s": round(unified_wall, 3),
        "disagg_s": round(disagg_wall, 3),
        "budget_frac": DISAGG_OVERHEAD_FRAC,
        "floor_s": DISAGG_OVERHEAD_FLOOR_S,
    }
    if len(done_disagg) != len(reqs) or len(done_unified) != len(reqs):
        raise PerfBudgetError(
            f"handoff overhead run drained {len(done_disagg)}/{len(reqs)} "
            f"disagg vs {len(done_unified)} unified"
        )
    if router.fallbacks:
        raise PerfBudgetError(
            f"handoff overhead run fell back {router.fallbacks} times on a "
            f"fault-free channel — every transfer must deliver"
        )
    if disagg_syncs > sync_ceiling:
        raise PerfBudgetError(
            f"disaggregation added device work: {disagg_syncs} host syncs "
            f"across the pair vs ceiling {sync_ceiling} (unified "
            f"{unified.host_syncs} + one KV capture per request) — the "
            f"handoff path is syncing beyond the one capture per transfer"
        )
    if disagg_wall > budget:
        raise PerfBudgetError(
            f"disagg pump took {disagg_wall:.3f}s > {budget:.3f}s "
            f"({unified_wall:.3f}s unified + {DISAGG_OVERHEAD_FRAC:.0%} + "
            f"{DISAGG_OVERHEAD_FLOOR_S}s floor): channel/router bookkeeping "
            f"is no longer cheap host work"
        )
    return stats


# The real wire may not cost device work: KVSlice.to_wire/from_wire run
# on the already-captured host bytes (numpy + crc32), and the frame
# exchange is socket/deque bookkeeping.  A loopback TransportChannel
# therefore pays EXACTLY the in-process channel's host syncs — any extra
# sync means the transport added a device->host readback per transfer.
TRANSPORT_OVERHEAD_FRAC = 0.50
TRANSPORT_OVERHEAD_FLOOR_S = 0.25


def check_transport_overhead() -> dict:
    """Budget guard for the KV transport (PR 13 tentpole): a DisaggRouter
    whose channel physically wire-encodes every payload, ships it across
    a loopback conn, and waits for the receiver's decode ACK must
    dispatch exactly the device work of the same router on the
    in-process channel, and the codec/framing host work stays inside a
    wall-clock envelope."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, disagg, serve, transport

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
        )

    reqs = [{"prompt": p, "max_tokens": 16} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    pre_i, dec_i = engine(), engine()
    inproc = disagg.DisaggRouter(prefill=[pre_i], decode=[dec_i])
    start = time.perf_counter()
    done_inproc = inproc.pump([dict(r) for r in reqs])
    inproc_wall = time.perf_counter() - start

    a, b = transport.LoopbackConn.pair()
    receiver = transport.WireReceiver(b)
    link = transport.PeerLink("overhead-peer", a)
    channel = transport.TransportChannel(link, peer_pump=receiver.pump)
    pre_w, dec_w = engine(), engine()
    wired = disagg.DisaggRouter(
        prefill=[pre_w], decode=[dec_w], channel=channel
    )
    start = time.perf_counter()
    done_wired = wired.pump([dict(r) for r in reqs])
    wired_wall = time.perf_counter() - start

    inproc_syncs = pre_i.host_syncs + dec_i.host_syncs
    wired_syncs = pre_w.host_syncs + dec_w.host_syncs
    budget = inproc_wall * (1 + TRANSPORT_OVERHEAD_FRAC) + TRANSPORT_OVERHEAD_FLOOR_S
    stats = {
        "requests_inproc": len(done_inproc),
        "requests_wired": len(done_wired),
        "host_syncs_inproc": inproc_syncs,
        "host_syncs_wired": wired_syncs,
        "transfers_ok": channel.counts.get(disagg.OK, 0),
        "frames_decoded": len(receiver.delivered),
        "inproc_s": round(inproc_wall, 3),
        "wired_s": round(wired_wall, 3),
        "budget_frac": TRANSPORT_OVERHEAD_FRAC,
        "floor_s": TRANSPORT_OVERHEAD_FLOOR_S,
    }
    if len(done_wired) != len(reqs) or len(done_inproc) != len(reqs):
        raise PerfBudgetError(
            f"transport overhead run drained {len(done_wired)}/{len(reqs)} "
            f"wired vs {len(done_inproc)} in-process"
        )
    if wired.fallbacks or channel.counts.get(disagg.OK, 0) != len(reqs):
        raise PerfBudgetError(
            f"transport overhead run fell back {wired.fallbacks} times with "
            f"{channel.counts} on a fault-free loopback — every transfer "
            f"must cross the wire and ACK ok"
        )
    if wired_syncs != inproc_syncs:
        raise PerfBudgetError(
            f"the wire added device work: {wired_syncs} host syncs through "
            f"the loopback transport vs {inproc_syncs} in-process — the "
            f"codec must run on already-captured host bytes"
        )
    if wired_wall > budget:
        raise PerfBudgetError(
            f"wired pump took {wired_wall:.3f}s > {budget:.3f}s "
            f"({inproc_wall:.3f}s in-process + {TRANSPORT_OVERHEAD_FRAC:.0%} "
            f"+ {TRANSPORT_OVERHEAD_FLOOR_S}s floor): framing/codec is no "
            f"longer cheap host work"
        )
    return stats


# The autoscaler is a control law over stats() snapshots the router
# already collects: a 1-replica fleet under a no-op autoscaler (min ==
# max == 1, so no scaling action is ever legal) pays EXACTLY the bare
# fleet's host syncs, never touches the engine factory, and its per-tick
# vote (util/queue thresholds, hysteresis counters) stays inside the
# same wall envelope the router itself is held to.
AUTOSCALER_OVERHEAD_FRAC = 0.10
AUTOSCALER_OVERHEAD_FLOOR_S = 0.10


def check_autoscaler_overhead() -> dict:
    """Budget guard for the closed-loop autoscaler (PR 12 tentpole): a
    1-replica fleet pumped with a pinned FleetAutoscaler attached must
    dispatch exactly the device work of the same fleet without one."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, fleet, serve
    from k8s_dra_driver_tpu.models.autoscaler import (
        AutoscalerPolicy,
        FleetAutoscaler,
    )

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
        )

    reqs = [{"prompt": p, "max_tokens": 16} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    bare_eng = engine()
    bare = fleet.FleetRouter([bare_eng])
    start = time.perf_counter()
    done_bare = bare.pump([dict(r) for r in reqs])
    bare_wall = time.perf_counter() - start

    scaled_eng = engine()
    router = fleet.FleetRouter([scaled_eng])
    factory_calls = []

    def factory():
        factory_calls.append(1)
        return engine()

    asc = FleetAutoscaler(
        router,
        engine_factory=factory,
        policy=AutoscalerPolicy(min_replicas=1, max_replicas=1),
    ).attach()
    start = time.perf_counter()
    done_scaled = router.pump([dict(r) for r in reqs])
    scaled_wall = time.perf_counter() - start

    budget = bare_wall * (1 + AUTOSCALER_OVERHEAD_FRAC) + AUTOSCALER_OVERHEAD_FLOOR_S
    stats = {
        "requests_bare": len(done_bare),
        "requests_scaled": len(done_scaled),
        "host_syncs_bare": bare_eng.host_syncs,
        "host_syncs_scaled": scaled_eng.host_syncs,
        "autoscaler_ticks": asc.ticks,
        "autoscaler_actions": asc.actions,
        "bare_s": round(bare_wall, 3),
        "scaled_s": round(scaled_wall, 3),
        "budget_frac": AUTOSCALER_OVERHEAD_FRAC,
        "floor_s": AUTOSCALER_OVERHEAD_FLOOR_S,
    }
    if len(done_scaled) != len(reqs) or len(done_bare) != len(reqs):
        raise PerfBudgetError(
            f"autoscaler overhead run drained {len(done_scaled)}/{len(reqs)} "
            f"scaled vs {len(done_bare)} bare"
        )
    if asc.ticks == 0:
        raise PerfBudgetError(
            "attached autoscaler never ticked during the pump — the "
            "router tick hook is not being driven"
        )
    if asc.actions != 0 or factory_calls or len(router.replicas) != 1:
        raise PerfBudgetError(
            f"pinned autoscaler acted: {asc.actions} actions, "
            f"{len(factory_calls)} factory calls, {len(router.replicas)} "
            f"replicas — min==max==1 must make every scaling action illegal"
        )
    if scaled_eng.host_syncs != bare_eng.host_syncs:
        raise PerfBudgetError(
            f"autoscaler added device work: {scaled_eng.host_syncs} host "
            f"syncs with the control loop attached vs {bare_eng.host_syncs} "
            f"bare — the vote must stay host-side arithmetic over stats() "
            f"snapshots the router already holds"
        )
    if scaled_wall > budget:
        raise PerfBudgetError(
            f"autoscaled pump took {scaled_wall:.3f}s > {budget:.3f}s "
            f"({bare_wall:.3f}s bare + {AUTOSCALER_OVERHEAD_FRAC:.0%} + "
            f"{AUTOSCALER_OVERHEAD_FLOOR_S}s floor): the per-tick vote is "
            f"no longer cheap host work"
        )
    return stats


# The observability plane is pumped from the SAME loop the engines
# already run on: a cadence tick exports the journal/span rings via seq
# cursors and re-renders the metrics registry — pure host work over
# already-host-resident state, never a device readback — so an engine
# with the federation shipper attached pays EXACTLY the bare engine's
# host syncs.  Frame bodies are hard-capped by TelemetryShipper._fit at
# TELEM_BUDGET_BYTES (48 KiB) per burst, the documented ceiling that
# keeps a telemetry tick two orders of magnitude under a paged-KV layer
# shard on the shared socket.
OBS_PLANE_OVERHEAD_FRAC = 0.50
OBS_PLANE_OVERHEAD_FLOOR_S = 0.25


def check_obs_plane_overhead() -> dict:
    """Budget guard for the fleet observability plane (PR 16 tentpole):
    a DisaggRouter driven tick-by-tick with a TelemetryShipper force-
    shipping EVERY tick (the worst cadence possible) must dispatch
    exactly the device work of the same router without one, every TELEM
    frame must fit the byte ceiling, and the snapshots must actually
    federate into a FleetObservability merger."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, disagg, obs_plane, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(8)
    ]

    def engine():
        return serve.ServeEngine(
            params=params, cfg=cfg, n_slots=4, prompt_bucket=16, sync_interval=8
        )

    reqs = [{"prompt": p, "max_tokens": 16} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    def drive(router, shipper=None):
        rids = [router.submit(r["prompt"], r["max_tokens"]) for r in reqs]
        done = []
        for _ in range(5000):
            router.tick()
            done += router.completions()
            if shipper is not None:
                shipper.maybe_ship(force=True)
            if len(done) == len(rids):
                break
        return done

    pre_b, dec_b = engine(), engine()
    bare = disagg.DisaggRouter(prefill=[pre_b], decode=[dec_b])
    start = time.perf_counter()
    done_bare = drive(bare)
    bare_wall = time.perf_counter() - start

    plane = obs_plane.FleetObservability()
    frame_sizes = []

    def send(body: bytes) -> None:
        frame_sizes.append(len(body))
        plane.ingest_wire("perf-w", body)

    shipper = obs_plane.TelemetryShipper(send, "perf-w", interval_s=0.0)
    pre_o, dec_o = engine(), engine()
    shipped = disagg.DisaggRouter(prefill=[pre_o], decode=[dec_o])
    start = time.perf_counter()
    done_shipped = drive(shipped, shipper)
    shipped_wall = time.perf_counter() - start

    bare_syncs = pre_b.host_syncs + dec_b.host_syncs
    shipped_syncs = pre_o.host_syncs + dec_o.host_syncs
    budget = bare_wall * (1 + OBS_PLANE_OVERHEAD_FRAC) + OBS_PLANE_OVERHEAD_FLOOR_S
    stats = {
        "requests_bare": len(done_bare),
        "requests_shipped": len(done_shipped),
        "host_syncs_bare": bare_syncs,
        "host_syncs_shipped": shipped_syncs,
        "telem_frames": shipper.shipped_frames,
        "telem_bytes": shipper.shipped_bytes,
        "telem_max_frame_bytes": max(frame_sizes, default=0),
        "telem_budget_bytes": obs_plane.TELEM_BUDGET_BYTES,
        "instances_federated": plane.stats()["instances"],
        "bare_s": round(bare_wall, 3),
        "shipped_s": round(shipped_wall, 3),
        "budget_frac": OBS_PLANE_OVERHEAD_FRAC,
        "floor_s": OBS_PLANE_OVERHEAD_FLOOR_S,
    }
    if len(done_shipped) != len(reqs) or len(done_bare) != len(reqs):
        raise PerfBudgetError(
            f"obs-plane overhead run drained {len(done_shipped)}/{len(reqs)} "
            f"shipped vs {len(done_bare)} bare"
        )
    if shipper.shipped_frames == 0 or plane.stats()["instances"] != ["perf-w"]:
        raise PerfBudgetError(
            f"federation never happened: {shipper.shipped_frames} frames, "
            f"instances {plane.stats()['instances']} — the twin-run proved "
            f"nothing"
        )
    if max(frame_sizes, default=0) > obs_plane.TELEM_BUDGET_BYTES:
        raise PerfBudgetError(
            f"a TELEM frame hit {max(frame_sizes)} bytes > the "
            f"{obs_plane.TELEM_BUDGET_BYTES} ceiling — the shipper's shed "
            f"order is not enforcing the budget"
        )
    if shipped_syncs != bare_syncs:
        raise PerfBudgetError(
            f"federation added device work: {shipped_syncs} host syncs with "
            f"the shipper attached vs {bare_syncs} bare — a telemetry tick "
            f"must be cursor exports + a registry render, never a readback"
        )
    if shipped_wall > budget:
        raise PerfBudgetError(
            f"shipped pump took {shipped_wall:.3f}s > {budget:.3f}s "
            f"({bare_wall:.3f}s bare + {OBS_PLANE_OVERHEAD_FRAC:.0%} + "
            f"{OBS_PLANE_OVERHEAD_FLOOR_S}s floor): per-tick export/encode "
            f"is no longer cheap host work"
        )
    return stats


# plan() at cluster scale (PR 15 tentpole): the allocation index keeps
# per-node device groups and an incrementally-maintained consumed set, so
# a single placement query against a 1k-node inventory is sub-millisecond
# dict work — it must NOT rescan every pool per call.  Measured ~0.45ms
# p50 / ~0.93ms p90 on an idle CPU runner; 10ms p90 absorbs shared-runner
# noise while sitting ~50x under what an O(pools) rescan per plan() would
# cost at this scale.
PLAN_SCALE_NODES = 1_000
PLAN_P90_CEILING_MS = 10.0
PLAN_P50_CEILING_MS = 5.0


def check_plan_scale() -> dict:
    """Budget guard for cluster-scale placement (PR 15 tentpole): a
    seeded churn slice against a 1k-node synthetic inventory must keep
    plan() latency flat (index-backed, not pool-rescanning) and account
    every claim exactly once while doing it."""
    from k8s_dra_driver_tpu.scheduler.cluster_sim import SimConfig, run_sim

    report = run_sim(SimConfig(
        seed=17, n_nodes=PLAN_SCALE_NODES, duration_s=45.0,
        arrival_rate=3.0, fanout=4, audit_interval_s=30.0,
    ))
    stats = {
        "n_nodes": report.n_nodes,
        "plan_samples": report.plan_samples,
        "plan_p50_ms": report.plan_p50_ms,
        "plan_p50_ceiling_ms": PLAN_P50_CEILING_MS,
        "plan_p90_ms": report.plan_p90_ms,
        "plan_p90_ceiling_ms": PLAN_P90_CEILING_MS,
        "bound": report.bound,
        "audit_failures": report.audit_failures,
        "leaked_claims": report.leaked_claims,
        "wall_s": report.wall_s,
    }
    if report.plan_samples < 100 or report.bound < 50:
        raise PerfBudgetError(
            f"plan-scale slice exercised only {report.plan_samples} plans / "
            f"{report.bound} binds — not a meaningful latency sample"
        )
    if report.audit_failures or report.leaked_claims:
        raise PerfBudgetError(
            f"plan-scale slice mis-accounted claims: "
            f"{report.audit_failures} audit failures, "
            f"{report.leaked_claims} leaked"
        )
    if report.plan_p50_ms > PLAN_P50_CEILING_MS:
        raise PerfBudgetError(
            f"plan() p50 {report.plan_p50_ms}ms > {PLAN_P50_CEILING_MS}ms at "
            f"{PLAN_SCALE_NODES} nodes: the common case is rescanning pools"
        )
    if report.plan_p90_ms > PLAN_P90_CEILING_MS:
        raise PerfBudgetError(
            f"plan() p90 {report.plan_p90_ms}ms > {PLAN_P90_CEILING_MS}ms at "
            f"{PLAN_SCALE_NODES} nodes: placement latency is no longer flat "
            f"in cluster size (index miss storm or per-call rebuild)"
        )
    return stats


def check_contention_overhead() -> dict:
    """Budget guard for the conflict-aware allocator (PR 18 tentpole):
    every conflict-avoidance lever — seeded tie shuffling, shard
    routing, per-attempt refetch, ContentionBackoff bookkeeping — must
    be free when there is nothing to avoid.  One scheduler, no storm:
    plan() latency must sit inside the SAME ceilings check_plan_scale
    pins, with zero conflicts and zero backoff stalls."""
    from k8s_dra_driver_tpu.scheduler.cluster_sim import (
        ContentionConfig,
        run_contention,
    )

    report = run_contention(ContentionConfig(
        seed=17, n_nodes=PLAN_SCALE_NODES, n_schedulers=1,
        work_items=96, gang_items=12, conflict_aware=True,
    ))
    stats = {
        "n_nodes": report.n_nodes,
        "n_schedulers": report.n_schedulers,
        "plan_samples": report.plan_samples,
        "plan_p50_ms": report.plan_p50_ms,
        "plan_p50_ceiling_ms": PLAN_P50_CEILING_MS,
        "plan_p90_ms": report.plan_p90_ms,
        "plan_p90_ceiling_ms": PLAN_P90_CEILING_MS,
        "committed_claims": report.committed_claims,
        "conflicts_total": report.conflicts_total,
        "wasted_attempts": report.wasted_attempts,
        "convergence_s": report.convergence_s,
    }
    if report.plan_samples < 100 or report.committed_claims < 50:
        raise PerfBudgetError(
            f"contention slice exercised only {report.plan_samples} plans / "
            f"{report.committed_claims} commits — not a meaningful sample"
        )
    if report.conflicts_total or report.lost_claims or report.double_committed:
        raise PerfBudgetError(
            f"uncontended run was not conflict-free: "
            f"{report.conflicts_total} conflicts, {report.lost_claims} lost, "
            f"{report.double_committed} double-committed"
        )
    if report.plan_p50_ms > PLAN_P50_CEILING_MS:
        raise PerfBudgetError(
            f"conflict-aware plan() p50 {report.plan_p50_ms}ms > "
            f"{PLAN_P50_CEILING_MS}ms at {PLAN_SCALE_NODES} nodes: "
            f"avoidance levers are taxing the uncontended path"
        )
    if report.plan_p90_ms > PLAN_P90_CEILING_MS:
        raise PerfBudgetError(
            f"conflict-aware plan() p90 {report.plan_p90_ms}ms > "
            f"{PLAN_P90_CEILING_MS}ms at {PLAN_SCALE_NODES} nodes: "
            f"avoidance levers are taxing the uncontended tail"
        )
    return stats


# Quantized KV pools must be free on the HOST axis: dequant is fused into
# the attention operand load on-device, so an int8-KV engine pays exactly
# the bf16/f32 path's host syncs for the same workload.  The capacity
# ratio is the feature's reason to exist — int8 blocks (values + f32
# scale) are under half a bf16 block's bytes, so an equal-HBM pool holds
# >= 1.9x reservable blocks.
QUANTIZED_CAPACITY_RATIO_FLOOR = 1.9


def check_quantized_decode() -> dict:
    """Budget guard for quantized KV-cache blocks (PR 17 tentpole): the
    int8 pool's dequant must ride inside the decode dispatch — ZERO extra
    host syncs vs the float-pool twin — and the equal-HBM capacity
    multiplier must hold at the `reservable_blocks` level the KV-demand
    ledger admits on."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, paged

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=1,
        d_ff=64, max_seq=64,
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompts = [
        list(map(int, burnin.sample_tokens(jax.random.PRNGKey(s), cfg, batch=1, seq=8)[0]))
        for s in range(4)
    ]

    def pump(kv_dtype):
        eng = paged.PagedServeEngine(
            params=params, cfg=cfg, n_slots=2, n_blocks=24, block_size=16,
            prompt_bucket=16, attn_impl="xla", sync_interval=8,
            kv_dtype=kv_dtype,
        )
        eng.pump([(prompts[0], 8)])  # compile off the clock
        eng.host_syncs = 0
        done = eng.pump([(p, 8) for p in prompts])
        return eng, done

    base_eng, base_done = pump(None)
    q_eng, q_done = pump("int8")
    hbm = 24 * paged.kv_block_bytes(cfg, 16, "bfloat16")
    cap_bf16 = paged.PagedServeEngine(
        params=params, cfg=cfg, n_slots=2, block_size=16, prompt_bucket=16,
        attn_impl="xla", cache_dtype="bfloat16", pool_hbm_bytes=hbm,
    ).reservable_blocks
    cap_int8 = paged.PagedServeEngine(
        params=params, cfg=cfg, n_slots=2, block_size=16, prompt_bucket=16,
        attn_impl="xla", kv_dtype="int8", pool_hbm_bytes=hbm,
    ).reservable_blocks
    ratio = cap_int8 / cap_bf16
    stats = {
        "requests": len(q_done),
        "host_syncs_float": base_eng.host_syncs,
        "host_syncs_int8": q_eng.host_syncs,
        "reservable_bf16": cap_bf16,
        "reservable_int8": cap_int8,
        "capacity_ratio": round(ratio, 3),
        "capacity_ratio_floor": QUANTIZED_CAPACITY_RATIO_FLOOR,
    }
    if len(q_done) != len(prompts) or len(base_done) != len(prompts):
        raise PerfBudgetError(
            f"quantized decode drained {len(q_done)}/{len(prompts)} requests"
        )
    if q_eng.host_syncs != base_eng.host_syncs:
        raise PerfBudgetError(
            f"int8-KV decode paid {q_eng.host_syncs} host syncs vs "
            f"{base_eng.host_syncs} on the float pool — dequant leaked out "
            f"of the fused attention load onto the host axis"
        )
    if ratio < QUANTIZED_CAPACITY_RATIO_FLOOR:
        raise PerfBudgetError(
            f"int8-KV capacity ratio {ratio:.2f}x < "
            f"{QUANTIZED_CAPACITY_RATIO_FLOOR}x at equal HBM "
            f"({cap_int8} vs {cap_bf16} reservable blocks) — the "
            f"bytes-per-block win is not reaching the admission ledger"
        )
    return stats


# On-device sampling lets sync_interval grow past 16 for free: one burst
# is ONE compiled dispatch and ONE stacked-trace readback regardless of K.
ONDEVICE_SAMPLING_INTERVAL = 32


def check_ondevice_sampling() -> dict:
    """Budget guard for the on-device sampling burst (PR 17 tentpole): at
    ``sync_interval=32`` one ``step_burst`` on EACH engine kind pays
    exactly 1 burst dispatch + 1 device->host readback — sampling and the
    stop mask live in the scanned program, and the token/active/bad
    planes ride one stacked array."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, paged, serve

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    prompt = list(map(
        int, burnin.sample_tokens(jax.random.PRNGKey(3), cfg, batch=1, seq=8)[0]
    ))
    stats: dict = {"sync_interval": ONDEVICE_SAMPLING_INTERVAL}
    k = ONDEVICE_SAMPLING_INTERVAL

    def burst_counts(eng, wrap_dispatch):
        # submit + warm one full burst so compiles are off the books, then
        # count the readbacks and dispatches of ONE burst.
        eng.submit(prompt, max_tokens=k + 4, temperature=0.8, seed=11)
        eng.step_burst()
        counts = {"readbacks": 0, "dispatches": 0}
        orig_rb = eng._readback

        def counting_rb(x):
            counts["readbacks"] += 1
            return orig_rb(x)

        eng._readback = counting_rb
        wrap_dispatch(eng, counts)
        stepped = eng.step_burst()
        eng._readback = orig_rb
        return counts, stepped

    def wrap_dense(eng, counts):
        orig = eng._pipe_fn

        def counting_pipe(*a, **kw):
            counts["dispatches"] += 1
            return orig(*a, **kw)

        eng._pipe_fn = counting_pipe

    def wrap_paged(eng, counts):
        orig = eng._burst_fn

        def counting_burst(kk):
            fn = orig(kk)

            def call(*a, **kw):
                counts["dispatches"] += 1
                return fn(*a, **kw)

            return call

        eng._burst_fn = counting_burst

    dense = serve.ServeEngine(
        params=params, cfg=cfg, n_slots=2, prompt_bucket=16, sync_interval=k
    )
    d_counts, d_stepped = burst_counts(dense, wrap_dense)
    pag = paged.PagedServeEngine(
        params=params, cfg=cfg, n_slots=2, n_blocks=24, block_size=16,
        prompt_bucket=16, attn_impl="xla", sync_interval=k,
    )
    p_counts, p_stepped = burst_counts(pag, wrap_paged)
    stats.update(
        dense_readbacks=d_counts["readbacks"],
        dense_dispatches=d_counts["dispatches"],
        paged_readbacks=p_counts["readbacks"],
        paged_dispatches=p_counts["dispatches"],
    )
    if d_stepped < 1 or p_stepped < 1:
        raise PerfBudgetError(
            "on-device sampling burst had no active slots to measure"
        )
    for kind, c in (("dense", d_counts), ("paged", p_counts)):
        if c["dispatches"] != 1 or c["readbacks"] != 1:
            raise PerfBudgetError(
                f"{kind} sync_interval={k} burst paid {c['dispatches']} "
                f"dispatches + {c['readbacks']} readbacks, not 1 + 1 — "
                f"sampling or the stop mask fell back to the host"
            )
    return stats


# The fleet prefix tier's bet (models/fleet_prefix.py): index publish and
# lookup are pure host-side dict/digest work riding hooks the engines
# already fire — a tier-attached fleet on DISTINCT prompts (all misses,
# nothing to pull) dispatches EXACTLY the bare fleet's device work, and
# the miss-path prepare() itself stays sub-millisecond at p50.
PREFIX_OVERHEAD_FRAC = 0.50
PREFIX_OVERHEAD_FLOOR_S = 0.25
PREFIX_LOOKUP_P50_CEILING_S = 0.002


def check_prefix_fleet_overhead() -> dict:
    """Budget guard for the fleet prefix-cache tier: zero added host
    syncs on the miss path (publish/lookup are host-only), bounded wall
    overhead, and a p50 ceiling on the admission-time lookup itself."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, fleet, fleet_prefix, paged

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return paged.PagedServeEngine(
            params=params, cfg=cfg, n_slots=4, n_blocks=64, block_size=4,
            prompt_bucket=16, attn_impl="xla", sync_interval=8,
            prefix_cache_blocks=16,
        )

    # DISTINCT prompts: no cross-request reuse, so every admission is a
    # pure index miss — the tier may classify, never warm.
    prompts = [[(17 * i + 3 * j + 1) % 63 + 1 for j in range(10)]
               for i in range(8)]
    reqs = [{"prompt": p, "max_tokens": 8} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    bare_eng = engine()
    bare = fleet.FleetRouter([bare_eng])
    start = time.perf_counter()
    done_bare = bare.pump([dict(r) for r in reqs])
    bare_wall = time.perf_counter() - start

    tiered_eng = engine()
    tiered = fleet.FleetRouter([tiered_eng])
    tier = fleet_prefix.FleetPrefixTier()
    tiered.attach_prefix_tier(tier)
    start = time.perf_counter()
    done_tiered = tiered.pump([dict(r) for r in reqs])
    tiered_wall = time.perf_counter() - start

    # Time the miss-path lookup alone: fresh distinct prompts against the
    # now-populated index (each pumped prompt published its rungs).
    samples = []
    for i in range(200):
        p = [(29 * i + 5 * j + 2) % 63 + 1 for j in range(10)]
        t0 = time.perf_counter()
        verdict = tier.prepare("probe", tiered_eng, p, max_tokens=8)
        samples.append(time.perf_counter() - t0)
        if verdict != "cold":
            raise PerfBudgetError(
                f"distinct-prompt probe classified {verdict!r}, not 'cold' — "
                f"the miss-path timing sample is contaminated"
            )
    samples.sort()
    lookup_p50 = samples[len(samples) // 2]

    budget = bare_wall * (1 + PREFIX_OVERHEAD_FRAC) + PREFIX_OVERHEAD_FLOOR_S
    stats = {
        "requests_bare": len(done_bare),
        "requests_tiered": len(done_tiered),
        "host_syncs_bare": bare_eng.host_syncs,
        "host_syncs_tiered": tiered_eng.host_syncs,
        "index_entries": len(tier.index),
        "published_total": tier.index.published_total,
        "bare_s": round(bare_wall, 3),
        "tiered_s": round(tiered_wall, 3),
        "lookup_p50_s": round(lookup_p50, 6),
        "lookup_p50_ceiling_s": PREFIX_LOOKUP_P50_CEILING_S,
        "budget_frac": PREFIX_OVERHEAD_FRAC,
        "floor_s": PREFIX_OVERHEAD_FLOOR_S,
    }
    if len(done_tiered) != len(reqs) or len(done_bare) != len(reqs):
        raise PerfBudgetError(
            f"prefix overhead run drained {len(done_tiered)}/{len(reqs)} "
            f"tiered vs {len(done_bare)} bare"
        )
    if tiered_eng.host_syncs != bare_eng.host_syncs:
        raise PerfBudgetError(
            f"prefix tier added device work on the miss path: "
            f"{tiered_eng.host_syncs} host syncs tiered vs "
            f"{bare_eng.host_syncs} bare — publish/lookup must stay "
            f"host-side dict work"
        )
    if tier.index.published_total == 0:
        raise PerfBudgetError(
            "tier-attached fleet published nothing — the on_prefix_store "
            "hook came unwired, so the overhead being measured is not the "
            "tier's"
        )
    if tiered_wall > budget:
        raise PerfBudgetError(
            f"tiered pump took {tiered_wall:.3f}s > {budget:.3f}s "
            f"({bare_wall:.3f}s bare + {PREFIX_OVERHEAD_FRAC:.0%} + "
            f"{PREFIX_OVERHEAD_FLOOR_S}s floor)"
        )
    if lookup_p50 > PREFIX_LOOKUP_P50_CEILING_S:
        raise PerfBudgetError(
            f"prefix lookup p50 {lookup_p50 * 1e3:.3f}ms > "
            f"{PREFIX_LOOKUP_P50_CEILING_S * 1e3:.1f}ms ceiling — the "
            f"admission-time miss path stopped being cheap host work"
        )
    return stats


# The gossip publisher's bet (models/fleet_prefix.py PrefixGossip): the
# PREFIXPUB/PREFIXWDL plane is pure host-side dict/json work riding the
# worker pump cadence — a gossip-attached engine dispatches EXACTLY the
# bare engine's device work, and a publish storm ships under the TELEM
# byte budget with the shallow tail priority-shed (delayed, never lost).
def check_prefix_gossip_overhead() -> dict:
    """Budget guard for the wire gossip plane: zero added host syncs on a
    gossip-attached engine, every shipped frame under GOSSIP_BUDGET_BYTES,
    and storm shedding accounted — shed events requeue and drain."""
    import jax

    from k8s_dra_driver_tpu.models import burnin, fleet_prefix, paged

    cfg = burnin.ModelConfig(
        vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
    )
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return paged.PagedServeEngine(
            params=params, cfg=cfg, n_slots=4, n_blocks=64, block_size=4,
            prompt_bucket=16, attn_impl="xla", sync_interval=8,
            prefix_cache_blocks=16,
        )

    prompts = [[(17 * i + 3 * j + 1) % 63 + 1 for j in range(10)]
               for i in range(8)]
    reqs = [{"prompt": p, "max_tokens": 8} for p in prompts]
    engine().pump([dict(r) for r in reqs[:1]])  # compile off the clock

    bare_eng = engine()
    done_bare = bare_eng.pump([dict(r) for r in reqs])

    frames: list = []
    gossiped_eng = engine()
    gossip = fleet_prefix.PrefixGossip(
        lambda kind, body: frames.append((kind, body)))
    gossip.bind_engine(gossiped_eng)
    gossip.resync(1)
    done_gossiped = gossiped_eng.pump([dict(r) for r in reqs])
    gossip.maybe_ship(force=True)

    stats = {
        "requests_bare": len(done_bare),
        "requests_gossiped": len(done_gossiped),
        "host_syncs_bare": bare_eng.host_syncs,
        "host_syncs_gossiped": gossiped_eng.host_syncs,
        "shipped_frames": gossip.shipped_frames,
        "max_frame_bytes": gossip.max_frame_bytes,
        "budget_bytes": fleet_prefix.GOSSIP_BUDGET_BYTES,
    }
    if len(done_gossiped) != len(reqs) or len(done_bare) != len(reqs):
        raise PerfBudgetError(
            f"gossip overhead run drained {len(done_gossiped)}/{len(reqs)} "
            f"gossiped vs {len(done_bare)} bare"
        )
    if gossiped_eng.host_syncs != bare_eng.host_syncs:
        raise PerfBudgetError(
            f"gossip publisher added device work: "
            f"{gossiped_eng.host_syncs} host syncs gossiped vs "
            f"{bare_eng.host_syncs} bare — note_store/note_evict must stay "
            f"host-side dict work"
        )
    if gossip.shipped_frames == 0 or not gossip._held:
        raise PerfBudgetError(
            "gossip-attached engine shipped nothing — the on_prefix_store "
            "hook came unwired, so the overhead being measured is not the "
            "publisher's"
        )
    if gossip.max_frame_bytes > fleet_prefix.GOSSIP_BUDGET_BYTES:
        raise PerfBudgetError(
            f"gossip frame of {gossip.max_frame_bytes}B exceeds the "
            f"{fleet_prefix.GOSSIP_BUDGET_BYTES}B budget"
        )

    # Publish storm under a tiny budget: deepest rungs ship first, the
    # shallow tail is SHED (accounted) and drains on later ticks — the
    # budget bounds frame size, never loses a publish.
    storm_frames: list = []
    storm = fleet_prefix.PrefixGossip(
        lambda kind, body: storm_frames.append(body), budget_bytes=2048)
    storm.resync(1)
    geom = {"block_size": 4, "kv_dtype": "float32", "n_layers": 1,
            "kv_heads": 2, "head_dim": 16}
    for i in range(200):
        storm.note_store(tuple(range(i + 1)), i + 1, 0, geom)
    storm.maybe_ship(force=True)
    stats["storm_shed_total"] = storm.shed_total
    stats["storm_max_frame_bytes"] = storm.max_frame_bytes
    if storm.shed_total == 0:
        raise PerfBudgetError(
            "publish storm shed nothing under a 2KiB budget — priority "
            "shedding is unwired, so frame sizes are unbounded"
        )
    if storm.max_frame_bytes > 2048:
        raise PerfBudgetError(
            f"storm frame of {storm.max_frame_bytes}B exceeds its 2048B "
            f"budget — shedding is not bounding the frame"
        )
    drain_ships = 0
    while storm.pending():
        if storm.maybe_ship(force=True) == 0:
            raise PerfBudgetError(
                "shed publishes stopped draining — 'delayed, never lost' "
                "is broken"
            )
        drain_ships += 1
        if drain_ships > 10_000:
            raise PerfBudgetError("shed drain did not converge")
    stats["storm_drain_frames"] = drain_ships
    total_events = sum(
        len(json.loads(f[fleet_prefix._GOSSIP_HEADER_BYTES:])["events"])
        for f in storm_frames
    )
    if total_events != 200:
        raise PerfBudgetError(
            f"storm shipped {total_events}/200 publish events — shed "
            f"events were lost, not delayed"
        )
    return stats


def main() -> int:
    try:
        stats = check()
        stats["pipelined_decode"] = check_pipelined_decode()
        stats["shed_fastpath"] = check_shed_fastpath()
        stats["telemetry_overhead"] = check_telemetry_overhead()
        stats["router_overhead"] = check_router_overhead()
        stats["handoff_overhead"] = check_handoff_overhead()
        stats["transport_overhead"] = check_transport_overhead()
        stats["autoscaler_overhead"] = check_autoscaler_overhead()
        stats["obs_plane_overhead"] = check_obs_plane_overhead()
        stats["plan_scale"] = check_plan_scale()
        stats["contention_overhead"] = check_contention_overhead()
        stats["quantized_decode"] = check_quantized_decode()
        stats["ondevice_sampling"] = check_ondevice_sampling()
        stats["prefix_fleet_overhead"] = check_prefix_fleet_overhead()
        stats["prefix_gossip_overhead"] = check_prefix_gossip_overhead()
    except PerfBudgetError as exc:
        print(f"perf-smoke FAILED: {exc}", file=sys.stderr)
        return 1
    print(json.dumps({"perf_smoke": stats}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
