"""Summarize a jax.profiler XPlane capture: where does the step time go?

Workflow (the train-MFU push): capture a profile through the bench —
``BENCH_PROFILE_DIR=/tmp/prof python bench.py`` — then

    python tools/xplane_summary.py /tmp/prof [--plane TPU] [--top 25]

prints per-op total durations from the device plane, grouped into coarse
buckets (fusion / matmul / attention-softmax / reduce / copy-layout /
elementwise / other), so the gap between the matmul-probe ceiling and
``train_mfu`` decomposes into attackable line items.  On TPU most HLO
time sits in ``fusion.N`` clusters whose names hide the fused root — a
dominant "fusion" bucket is the signal to open the capture in
xprof/TensorBoard where the fused HLO is visible.

Parses the ``*.xplane.pb`` protos with the XSpace schema that ships in
the baked tensorflow (``tensorflow.tsl.profiler.protobuf.xplane_pb2``);
the tensorboard profile plugin's own converter is broken against this TF
build (missing ``xspace_to_tools_data`` binding), so we read the planes
directly — it is just (plane -> line -> event(metadata_id, duration)).
"""

from __future__ import annotations

import argparse
import collections
import pathlib
import re
import sys

# Word-boundary anchors matter: XLA op names are dotted/suffixed
# ("convert.5", "expand_dims", "sort"), and bare substrings misroute them
# ("conv" would claim every convert as matmul, "exp" would claim
# expand_dims as attention) — corrupting exactly the matmul-vs-rest
# decomposition this tool exists to produce.  Fusions get their OWN
# bucket: on TPU nearly all HLO time sits in "fusion.N" clusters whose
# name says nothing about the fused root (elementwise loops, reduces and
# matmul epilogues all look alike), and claiming them for any one class
# would make the breakdown read as that class regardless of reality —
# a large "fusion" bucket is itself the signal to open the trace in
# xprof/TensorBoard where the fused HLO is visible.
_BUCKETS = (
    ("fusion", re.compile(r"\bfusion\b", re.I)),
    ("matmul", re.compile(r"\bdot\b|\bconv(olution)?\b|\bgemm\b", re.I)),
    ("attention/softmax", re.compile(
        r"softmax|\bexp(onential)?\b|attention|flash", re.I)),
    ("reduce/norm", re.compile(r"reduce|\bnorm\b|\bmean\b|variance", re.I)),
    ("copy/layout", re.compile(
        r"copy|transpose|reshape|bitcast|concat|slice|\bpad\b|gather|"
        r"scatter|dynamic|expand_dims", re.I)),
    ("elementwise", re.compile(
        r"\badd\b|\bsub\b|\bmul\b|\bdiv\b|\bmax\b|\bmin\b|select|compare|"
        r"tanh|rsqrt|convert|\band\b|\bor\b|\bxor\b", re.I)),
)


def _bucket(name: str) -> str:
    for label, rx in _BUCKETS:
        if rx.search(name):
            return label
    return "other"


def load_xspaces(profile_dir: str):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(pathlib.Path(profile_dir).rglob("*.xplane.pb"))
    if not paths:
        raise FileNotFoundError(f"no *.xplane.pb under {profile_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        xs.ParseFromString(p.read_bytes())
        spaces.append((p, xs))
    return spaces


def summarize(profile_dir: str, plane_filter: str = "TPU", top: int = 25) -> dict:
    spaces = load_xspaces(profile_dir)
    per_op: collections.Counter = collections.Counter()
    planes_seen: list[str] = []
    matched = False
    for _, xs in spaces:
        planes = [p for p in xs.planes if plane_filter.lower() in p.name.lower()]
        planes_seen.extend(p.name for p in xs.planes)
        if planes:
            matched = True
        for plane in planes:
            meta = {m.id: m.name for m in plane.event_metadata.values()}
            for line in plane.lines:
                # per-op lines only: step/module summary lines double-count
                if line.name.lower() in ("steps", "xla modules", "framework name scope"):
                    continue
                for ev in line.events:
                    name = meta.get(ev.metadata_id, f"op#{ev.metadata_id}")
                    per_op[name] += ev.duration_ps
    if not matched:
        raise ValueError(
            f"no plane matching {plane_filter!r}; planes present: "
            f"{sorted(set(planes_seen))}"
        )
    total = sum(per_op.values()) or 1
    buckets: collections.Counter = collections.Counter()
    for name, ps in per_op.items():
        buckets[_bucket(name)] += ps
    return {
        "total_ms": total / 1e9,
        "buckets": {
            k: {"ms": v / 1e9, "pct": 100.0 * v / total}
            for k, v in buckets.most_common()
        },
        "top_ops": [
            {"op": n, "ms": ps / 1e9, "pct": 100.0 * ps / total}
            for n, ps in per_op.most_common(top)
        ],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("profile_dir")
    ap.add_argument("--plane", default="TPU",
                    help="substring of the device plane name (use 'CPU' for host-only captures)")
    ap.add_argument("--top", type=int, default=25)
    args = ap.parse_args(argv)
    s = summarize(args.profile_dir, plane_filter=args.plane, top=args.top)
    print(f"device time: {s['total_ms']:.3f} ms across ops")
    print("\nbuckets:")
    for k, v in s["buckets"].items():
        print(f"  {k:<20} {v['ms']:>10.3f} ms  {v['pct']:5.1f}%")
    print(f"\ntop {len(s['top_ops'])} ops:")
    for row in s["top_ops"]:
        print(f"  {row['pct']:5.1f}%  {row['ms']:>10.3f} ms  {row['op']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
