#!/usr/bin/env bash
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

gcloud container clusters delete "${CLUSTER_NAME}" \
  --project "${PROJECT}" --location "${LOCATION}" --quiet
