#!/usr/bin/env bash
# Create a GKE cluster with DRA enabled plus a multi-host TPU nodepool.
# GKE provisions every host of the slice atomically and labels the nodes
# with cloud.google.com/gke-tpu-* — the pre-labeled slice-membership model
# the controller consumes (ARCHITECTURE.md hard-parts decision: don't solve
# cross-host bin-packing in-cluster, consume the provisioner's truth).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

gcloud container clusters create "${CLUSTER_NAME}" \
  --project "${PROJECT}" \
  --location "${LOCATION}" \
  --cluster-version "${CLUSTER_VERSION}" \
  --enable-kubernetes-unstable-apis=resource.k8s.io/v1beta1/deviceclasses,resource.k8s.io/v1beta1/resourceclaims,resource.k8s.io/v1beta1/resourceclaimtemplates,resource.k8s.io/v1beta1/resourceslices \
  --num-nodes 1

gcloud container node-pools create "${NODEPOOL_NAME}" \
  --project "${PROJECT}" \
  --location "${LOCATION}" \
  --cluster "${CLUSTER_NAME}" \
  --machine-type "${TPU_MACHINE_TYPE}" \
  --tpu-topology "${TPU_TOPOLOGY}" \
  --num-nodes "$(topology_hosts)"

echo "cluster ${CLUSTER_NAME} ready; next:"
echo "  demo/clusters/gke/scripts/label-slice-nodes.sh"
echo "  demo/clusters/gke/scripts/install-dra-driver.sh"
