#!/usr/bin/env bash
# Map GKE's TPU nodepool labels onto the slice-controller contract:
#   tpu.google.com/slice-domain   groups the slice's hosts
#   tpu.google.com/slice-host-id  each host's worker index
# GKE already exports the worker index as
# cloud.google.com/gke-tpu-worker-id on multi-host nodepools; this script
# just bridges the namespaces (the in-cluster label-sync sidecar equivalent,
# runnable from any admin shell and idempotent).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

selector="cloud.google.com/gke-tpu-topology=${TPU_TOPOLOGY}"
nodes=$(kubectl get nodes -l "${selector}" -o name)
if [[ -z "${nodes}" ]]; then
  echo "no nodes match ${selector}" >&2
  exit 1
fi

for node in ${nodes}; do
  worker_id=$(kubectl get "${node}" \
    -o jsonpath='{.metadata.labels.cloud\.google\.com/gke-tpu-worker-id}')
  if [[ -z "${worker_id}" ]]; then
    # Defaulting would label every such node host-id 0 and silently corrupt
    # the membership set; a missing worker id means this is not a multi-host
    # TPU nodepool (or the selector matched the wrong nodes).
    echo "ERROR: ${node} has no cloud.google.com/gke-tpu-worker-id label" >&2
    exit 1
  fi
  kubectl label --overwrite "${node}" \
    "tpu.google.com/slice-domain=${SLICE_DOMAIN}" \
    "tpu.google.com/slice-host-id=${worker_id}"
done

kubectl get nodes -l "tpu.google.com/slice-domain=${SLICE_DOMAIN}" \
  -L tpu.google.com/slice-host-id
