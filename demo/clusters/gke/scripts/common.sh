#!/usr/bin/env bash
# Shared settings for the GKE demo harness (reference demo/clusters/gke
# analog, re-flavored for TPU nodepools).
set -euo pipefail

: "${PROJECT:=$(gcloud config list --format 'value(core.project)' 2>/dev/null)}"
if [[ -z "${PROJECT}" ]]; then
  echo "no GCP project configured; run 'gcloud config set project <id>'" >&2
  exit 1
fi

: "${CLUSTER_NAME:=tpu-dra-driver-cluster}"
: "${LOCATION:=us-central2-b}"        # a zone with v5e/v4 capacity
: "${CLUSTER_VERSION:=1.32}"          # DRA structured parameters need >=1.32
: "${NODEPOOL_NAME:=tpu-slice}"
# Multi-host v5e: 4 chips/host machine, 4x8 topology = 8 hosts.
: "${TPU_MACHINE_TYPE:=ct5lp-hightpu-4t}"
: "${TPU_TOPOLOGY:=4x8}"
: "${CHIPS_PER_HOST:=4}"   # ct5lp-hightpu-4t exposes 4 chips per VM
: "${SLICE_DOMAIN:=${NODEPOOL_NAME}-${TPU_TOPOLOGY}}"

# Host count follows the topology product / chips-per-host, so overriding
# TPU_TOPOLOGY keeps --num-nodes consistent (gcloud rejects mismatches).
topology_hosts() {
  local product=1
  IFS=x read -ra dims <<< "${TPU_TOPOLOGY}"
  for d in "${dims[@]}"; do product=$((product * d)); done
  echo $((product / CHIPS_PER_HOST))
}

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../../.." && pwd)"
