#!/usr/bin/env bash
# Install the driver on GKE in REAL mode (no fakeTopology: libtpuinfo
# enumerates /dev/accel* and the GKE TPU runtime env).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

helm upgrade --install tpu-dra-driver \
  "${REPO_ROOT}/deployments/helm/tpu-dra-driver" \
  --namespace tpu-dra-driver --create-namespace \
  "$@"

kubectl -n tpu-dra-driver rollout status daemonset/tpu-dra-driver-kubelet-plugin --timeout=300s
kubectl get resourceslices
echo "apply demo/specs/quickstart/slice-test1.yaml to run the multi-host JAX job"
