#!/usr/bin/env bash
# Create a MULTI-NODE kind cluster wired for DRA + CDI, with the fake TPU
# topology so the full driver stack — including the multi-host slice
# controller — runs with zero TPU hardware.  Each kind worker impersonates
# one host of a ${FAKE_TOPOLOGY} slice via node labels:
#
#   tpu.google.com/fake-topology   what the worker's plugin enumerates
#   tpu.google.com/fake-host-id    which host block of the slice it owns
#   tpu.google.com/slice-domain    groups workers into one logical slice
#   tpu.google.com/slice-host-id   the worker id the controller publishes
#
# (The reference needs real GPUs injected into the kind worker and nvkind
# params masking for per-node subsets — demo/clusters/kind/scripts/
# kind-cluster-config.yaml:56-63, values.yaml:41-48; the fake libtpuinfo
# backend plus label-driven knobs replace both.)
source "$(dirname "${BASH_SOURCE[0]}")/scripts/common.sh"

workers() {
  for ((i = 0; i < NUM_WORKERS; i++)); do
    cat <<EOF
  - role: worker
    labels:
      tpu.google.com/fake-topology: "${FAKE_TOPOLOGY}"
      tpu.google.com/fake-host-id: "${i}"
      tpu.google.com/slice-domain: "${SLICE_DOMAIN}"
      tpu.google.com/slice-host-id: "${i}"
EOF
  done
}

cat <<EOF | kind create cluster --name "${CLUSTER_NAME}" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
featureGates:
  DynamicResourceAllocation: true
containerdConfigPatches:
  - |-
    [plugins."io.containerd.grpc.v1.cri"]
      enable_cdi = true
nodes:
  - role: control-plane
    kubeadmConfigPatches:
      - |
        kind: ClusterConfiguration
        apiServer:
          extraArgs:
            runtime-config: "resource.k8s.io/v1beta1=true"
$(workers)
EOF

echo "cluster ${CLUSTER_NAME} ready (${NUM_WORKERS} fake ${FAKE_TOPOLOGY} hosts)."
echo "next:"
echo "  demo/clusters/kind/scripts/build-driver-image.sh"
echo "  demo/clusters/kind/scripts/load-driver-image-into-kind.sh"
echo "  demo/clusters/kind/scripts/install-dra-driver.sh"
echo "  kubectl apply -f demo/specs/quickstart/tpu-test1.yaml"
