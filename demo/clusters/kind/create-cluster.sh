#!/usr/bin/env bash
# Create a kind cluster wired for DRA + CDI, with the fake TPU topology so
# the full driver stack runs with zero TPU hardware (the reference needs real
# GPUs injected into the kind worker — demo/clusters/kind/scripts/
# kind-cluster-config.yaml:56-63; our fake libtpuinfo backend removes that
# requirement entirely).
set -euo pipefail

CLUSTER_NAME="${CLUSTER_NAME:-tpu-dra-driver-cluster}"
FAKE_TOPOLOGY="${FAKE_TOPOLOGY:-v5e-16}"

cat <<EOF | kind create cluster --name "${CLUSTER_NAME}" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
featureGates:
  DynamicResourceAllocation: true
containerdConfigPatches:
  - |-
    [plugins."io.containerd.grpc.v1.cri"]
      enable_cdi = true
nodes:
  - role: control-plane
    kubeadmConfigPatches:
      - |
        kind: ClusterConfiguration
        apiServer:
          extraArgs:
            runtime-config: "resource.k8s.io/v1beta1=true"
  - role: worker
    labels:
      tpu.google.com/fake-topology: "${FAKE_TOPOLOGY}"
EOF

echo "cluster ${CLUSTER_NAME} ready; install the driver with:"
echo "  helm install tpu-dra-driver deployments/helm/tpu-dra-driver --set fakeTopology=${FAKE_TOPOLOGY}"
