#!/usr/bin/env bash
# Install the driver via helm (reference nvkind install-dra-driver.sh
# analog).  Per-node fake topology/host-id come from node LABELS (set by
# create-cluster.sh), so no per-node values overrides are needed — the
# plugin falls back to its node's tpu.google.com/fake-{topology,host-id}
# labels when the env knobs are unset.
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

helm upgrade --install tpu-dra-driver \
  "${REPO_ROOT}/deployments/helm/tpu-dra-driver" \
  --namespace tpu-dra-driver --create-namespace \
  --set image.repository="${DRIVER_IMAGE}" \
  --set image.tag="${DRIVER_IMAGE_TAG}" \
  --set image.pullPolicy=Never \
  "$@"

kubectl -n tpu-dra-driver rollout status daemonset/tpu-dra-driver-kubelet-plugin --timeout=180s
kubectl -n tpu-dra-driver rollout status deployment/tpu-dra-driver-controller --timeout=180s || true
kubectl get resourceslices
