#!/usr/bin/env bash
# Load the locally built driver image into every kind node (reference
# scripts/load-driver-image-into-kind.sh analog).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

kind load docker-image \
  --name "${CLUSTER_NAME}" \
  "${DRIVER_IMAGE}:${DRIVER_IMAGE_TAG}"
