#!/usr/bin/env bash
# Tear the demo cluster down (reference scripts/delete-kind-cluster.sh).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

kind delete cluster --name "${CLUSTER_NAME}"
