#!/usr/bin/env bash
# Build the driver image (reference scripts/build-driver-image.sh analog).
source "$(dirname "${BASH_SOURCE[0]}")/common.sh"

docker build \
  -t "${DRIVER_IMAGE}:${DRIVER_IMAGE_TAG}" \
  -f "${REPO_ROOT}/deployments/container/Dockerfile" \
  "${REPO_ROOT}"

echo "built ${DRIVER_IMAGE}:${DRIVER_IMAGE_TAG}"
