#!/usr/bin/env bash
# Shared settings for the kind demo harness (reference
# demo/clusters/kind/scripts/common.sh analog).
set -euo pipefail

: "${CLUSTER_NAME:=tpu-dra-driver-cluster}"
: "${DRIVER_IMAGE:=tpu-dra-driver}"
: "${DRIVER_IMAGE_TAG:=v0.1.0}"
# Per-worker fake topology: each kind worker impersonates one host of this
# multi-host slice (v5e-16 = 4 hosts x 4 chips).
: "${FAKE_TOPOLOGY:=v5e-16}"
# Workers in the cluster == fake hosts of the slice.  slice-test1.yaml runs
# 4 replicas with pod anti-affinity, so 4 workers exercise the full flow.
: "${NUM_WORKERS:=2}"
: "${SLICE_DOMAIN:=${FAKE_TOPOLOGY}-demo}"

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/../../../.." && pwd)"
