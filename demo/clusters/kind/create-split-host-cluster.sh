#!/usr/bin/env bash
# nvkind-analog variant: SPLIT ONE HOST'S CHIPS among several kind workers.
#
# The reference's nvkind flow gives each kind worker a distinct subset of
# the box's real GPUs via params masking (values.yaml:41-48,
# kubeletplugin.yaml:58-67).  Here the same per-worker-subset property is a
# node label: every worker impersonates THE SAME fake host
# (fake-host-id=0) but carries a disjoint tpu.google.com/visible-chips
# mask, so its plugin publishes only its share — disjoint uuids, disjoint
# chip markers, no double-booking (tests/test_visible_chips.py).
#
#   NUM_SPLITS=2 FAKE_TOPOLOGY=v5e-8 demo/clusters/kind/create-split-host-cluster.sh
#   -> worker 0 publishes chips {0,1}, worker 1 publishes chips {2,3}
#
# Label values cannot carry commas; the mask label uses '.' ("0.1").
source "$(dirname "${BASH_SOURCE[0]}")/scripts/common.sh"

: "${NUM_SPLITS:=2}"
# chips per host for the chosen fake topology (v5e-16: 4, v5e-8: 4, v5e-32: 4)
: "${CHIPS_PER_HOST:=4}"

if (( CHIPS_PER_HOST % NUM_SPLITS != 0 )); then
  echo "NUM_SPLITS (${NUM_SPLITS}) must divide CHIPS_PER_HOST (${CHIPS_PER_HOST})" >&2
  exit 2
fi
share=$(( CHIPS_PER_HOST / NUM_SPLITS ))

workers() {
  for ((i = 0; i < NUM_SPLITS; i++)); do
    mask=""
    for ((c = i * share; c < (i + 1) * share; c++)); do
      mask="${mask:+${mask}.}${c}"
    done
    cat <<EOF
  - role: worker
    labels:
      tpu.google.com/fake-topology: "${FAKE_TOPOLOGY}"
      tpu.google.com/fake-host-id: "0"
      tpu.google.com/visible-chips: "${mask}"
EOF
  done
}

cat <<EOF | kind create cluster --name "${CLUSTER_NAME}" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
featureGates:
  DynamicResourceAllocation: true
containerdConfigPatches:
  - |-
    [plugins."io.containerd.grpc.v1.cri"]
      enable_cdi = true
nodes:
  - role: control-plane
    kubeadmConfigPatches:
      - |
        kind: ClusterConfiguration
        apiServer:
          extraArgs:
            runtime-config: "resource.k8s.io/v1beta1=true"
$(workers)
EOF

echo "cluster ${CLUSTER_NAME} ready (${NUM_SPLITS} workers sharing one ${FAKE_TOPOLOGY} host, ${share} chips each)."
echo "next: the same build/load/install steps as create-cluster.sh, then:"
echo "  kubectl get resourceslices   # disjoint tpu-N inventories per worker"
