#!/usr/bin/env bash
# Emit env-var assignments for the walkthrough (reference
# demo/specs/mig+mps/sharing-demo-envs.sh analog): resolves the first demo
# pod and the CDI device IDs of each shared claim so the README's
# `kubectl exec` lines can be copy-pasted.
set -euo pipefail

ns=sharing-demo

pod=$(kubectl get pod -n "$ns" -l job-name=sharing-demo-job \
      -o jsonpath='{.items[0].metadata.name}')
echo "export SHARING_POD=${pod}"

for claim in chip-ts-sharing chip-sp-sharing subslice-ts-sharing subslice-exclusive; do
  uid=$(kubectl get resourceclaim -n "$ns" "$claim" -o jsonpath='{.metadata.uid}')
  var=$(echo "$claim" | tr '[:lower:]-' '[:upper:]_')
  echo "export ${var}_CLAIM_UID=${uid}"
done
